"""Serving engine: bucketed AOT compilation, concurrent dynamic batching,
pass pipeline, SLO telemetry — plus the PR-6 inference satellites.

Mirrors the reference's AnalysisPredictor contracts (`analysis_predictor.cc`
prepare/optimize/run + ZeroCopyTensor semantics) over the StableHLO
artifact: arbitrary ragged traffic must serve through <= len(bucket_ladder)
pre-compiled executables with NO request-path compiles, and padded-batch
outputs must be bitwise-equal (fp32) to per-request unbatched runs.
"""
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.serving as serving
from paddle_tpu import monitor
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.jit.io import save as jit_save
from paddle_tpu.jit.to_static import InputSpec
from paddle_tpu.observability import export as obs_export


def _mlp(in_dim=8, hidden=16, out_dim=4, seed=7):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(in_dim, hidden), nn.Tanh(),
                      nn.Linear(hidden, out_dim))
    m.eval()
    return m


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    """Saved batch-polymorphic StableHLO artifact + the live model."""
    model = _mlp()
    prefix = str(tmp_path_factory.mktemp("serving") / "m")
    jit_save(model, prefix,
             input_spec=[InputSpec([None, 8], "float32", name="feat")])
    return model, prefix


class TestBucketedAOT:
    def test_ragged_batches_bitwise_equal_unbatched(self, artifact):
        """Acceptance: padded-bucket outputs == per-request unbatched
        Predictor runs, bitwise (fp32)."""
        _model, prefix = artifact
        pred = create_predictor(Config(prefix + ".pdmodel",
                                       prefix + ".pdiparams"))
        with serving.Engine(prefix, bucket_ladder=(1, 4, 8),
                            batch_timeout_ms=1.0) as eng:
            rng = np.random.RandomState(0)
            for rows in (1, 2, 3, 4, 5, 7, 8):
                x = rng.randn(rows, 8).astype(np.float32)
                (want,) = pred.run([x])
                (got,) = eng.predict(x)
                assert got.dtype == np.float32
                np.testing.assert_array_equal(got, want)

    def test_bucket_selection(self, artifact):
        _model, prefix = artifact
        with serving.Engine(prefix, bucket_ladder=(1, 4, 8)) as eng:
            assert [eng.bucket_for(r) for r in (1, 2, 4, 5, 8)] == \
                [1, 4, 4, 8, 8]
            with pytest.raises(ValueError, match="exceed"):
                eng.bucket_for(9)

    def test_ladder_executables_no_request_path_compiles(self, artifact):
        """Acceptance: <= len(bucket_ladder) compiled executables, zero
        compiles on the request path after warmup — counter evidence via
        the jax backend-compile hook AND the engine's own AOT counter."""
        import paddle_tpu.observability as obs
        _model, prefix = artifact
        obs.enable()
        try:
            eng = serving.Engine(prefix, bucket_ladder=(1, 4, 8),
                                 batch_timeout_ms=1.0)
            assert eng.aot_compiles == 3 == len(eng.bucket_ladder)
            compiles_after_load = monitor.stats().get(
                "jit_backend_compiles", 0)
            aot_after_load = monitor.stats()["serving_aot_compiles"]
            rng = np.random.RandomState(1)
            for rows in (2, 1, 5, 3, 8, 7, 4, 6):  # every bucket, ragged
                eng.predict(rng.randn(rows, 8).astype(np.float32))
            assert monitor.stats().get("jit_backend_compiles", 0) == \
                compiles_after_load
            assert monitor.stats()["serving_aot_compiles"] == aot_after_load
            assert eng.stats()["executables"] == 3
            eng.close()
        finally:
            obs.disable()

    def test_oversized_request_chunks_transparently(self, artifact):
        model, prefix = artifact
        with serving.Engine(prefix, bucket_ladder=(1, 4),
                            batch_timeout_ms=1.0) as eng:
            x = np.random.RandomState(2).randn(11, 8).astype(np.float32)
            (got,) = eng.predict(x)
            np.testing.assert_array_equal(got, model(Tensor(x)).numpy())
            assert eng.stats()["chunked_requests"] == 1

    def test_input_validation(self, artifact):
        _model, prefix = artifact
        with serving.Engine(prefix, bucket_ladder=(4,)) as eng:
            with pytest.raises(ValueError, match="expected 1 inputs"):
                eng.predict(np.ones((2, 8), np.float32),
                            np.ones((2, 8), np.float32))
            with pytest.raises(ValueError, match="got shape"):
                eng.predict(np.ones((2, 9), np.float32))
            with pytest.raises(ValueError, match="empty request"):
                eng.predict(np.zeros((0, 8), np.float32))

    def test_non_batch_major_output_rejected(self):
        """A fetch whose axis 0 is not the batch can't be sliced back to
        requests — the engine must refuse at load, not serve garbage."""
        paddle.seed(0)
        from paddle_tpu import static
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [-1, 4], "float32")
            w = static.create_parameter([4, 4], "float32")
            red = paddle.sum(paddle.matmul(x, w))  # batch-reduced
        with pytest.raises(ValueError, match="not batch-major"):
            serving.Engine.from_program(prog, [red], bucket_ladder=(2,))

    def test_unreachable_buckets_not_compiled(self, artifact):
        """max_batch_size caps batch rows, so ladder buckets above it can
        never be selected — compiling them would waste load latency."""
        model, prefix = artifact
        with serving.Engine(prefix, bucket_ladder=(1, 4, 16),
                            max_batch_size=4,
                            batch_timeout_ms=1.0) as eng:
            assert eng.bucket_ladder == (1, 4)
            assert eng.aot_compiles == 2
            x = np.random.RandomState(21).randn(7, 8).astype(np.float32)
            (got,) = eng.predict(x)  # chunks through the 4-bucket
            np.testing.assert_array_equal(got, model(Tensor(x)).numpy())

    def test_fixed_batch_artifact_rejected(self, tmp_path):
        model = _mlp()
        prefix = str(tmp_path / "fixed")
        jit_save(model, prefix, input_spec=[InputSpec([2, 8], "float32")])
        with pytest.raises(ValueError, match="batch-polymorphic"):
            serving.Engine(prefix, bucket_ladder=(1, 4))


class TestConcurrentBatching:
    def test_concurrent_clients_coalesce(self, artifact):
        """N threads of ragged traffic: every future resolves with correct
        rows, and at least one device step served multiple requests."""
        model, prefix = artifact
        with serving.Engine(prefix, bucket_ladder=(1, 4, 16),
                            batch_timeout_ms=20.0) as eng:
            results = {}

            def client(i):
                rng = np.random.RandomState(100 + i)
                for j in range(5):
                    x = rng.randn(1 + (i + j) % 3, 8).astype(np.float32)
                    results[(i, j)] = (x, eng.predict(x))

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            stats = eng.stats()
        assert len(results) == 40
        for x, (out,) in results.values():
            assert out.shape[0] == x.shape[0]
            np.testing.assert_array_equal(out, model(Tensor(x)).numpy())
        assert stats["requests"] == 40
        assert stats["multi_request_batches"] >= 1
        assert stats["batches"] < 40  # coalescing actually happened

    def test_timeout_flushes_partial_batch(self, artifact):
        """A lone request must not wait for a full bucket: the
        batch_timeout_ms window flushes it."""
        _model, prefix = artifact
        with serving.Engine(prefix, bucket_ladder=(16,),
                            batch_timeout_ms=30.0) as eng:
            t0 = time.perf_counter()
            (out,) = eng.predict(np.ones((2, 8), np.float32))
            dt = time.perf_counter() - t0
            assert out.shape == (2, 4)
            assert dt < 10.0  # flushed by timeout, not stuck
            assert eng.stats()["padded_rows"] == 14
        g = obs_export.gauges()
        assert g["serving_batch_fill_ratio"] == pytest.approx(2 / 16)

    def test_submit_returns_future(self, artifact):
        _model, prefix = artifact
        with serving.Engine(prefix, bucket_ladder=(4,),
                            batch_timeout_ms=1.0) as eng:
            futs = [eng.submit(np.ones((1, 8), np.float32))
                    for _ in range(6)]
            outs = [f.result(timeout=30) for f in futs]
        assert all(o[0].shape == (1, 4) for o in outs)

    def test_cancelled_future_does_not_poison_batch(self, artifact):
        """A caller cancelling its queued future must not break the
        co-batched requests' results (regression: set_result on the
        cancelled future raised InvalidStateError into the batch)."""
        model, prefix = artifact
        with serving.Engine(prefix, bucket_ladder=(1, 4, 16),
                            batch_timeout_ms=200.0) as eng:
            x = np.random.RandomState(30).randn(2, 8).astype(np.float32)
            f1 = eng.submit(x)  # opens a long coalescing window
            f2 = eng.submit(np.ones((1, 8), np.float32))
            f2.cancel()  # walk away while queued
            (out,) = f1.result(timeout=30)
        np.testing.assert_array_equal(out, model(Tensor(x)).numpy())

    def test_close_rejects_new_requests(self, artifact):
        _model, prefix = artifact
        eng = serving.Engine(prefix, bucket_ladder=(4,))
        eng.close()
        with pytest.raises(RuntimeError, match="closed"):
            eng.predict(np.ones((1, 8), np.float32))


class TestPassPipeline:
    def test_fp32_from_layer_bitwise(self):
        model = _mlp(seed=11)
        x = np.random.RandomState(3).randn(5, 8).astype(np.float32)
        want = model(Tensor(x)).numpy()
        with serving.Engine.from_layer(
                model, [InputSpec([None, 8], "float32")],
                bucket_ladder=(1, 8), batch_timeout_ms=1.0) as eng:
            (got,) = eng.predict(x)
        np.testing.assert_array_equal(got, want)

    def test_bf16_pass_within_tolerance(self):
        model = _mlp(seed=12)
        x = np.random.RandomState(4).randn(6, 8).astype(np.float32)
        want = model(Tensor(x)).numpy()
        with serving.Engine.from_layer(
                model, [InputSpec([None, 8], "float32")],
                bucket_ladder=(8,), passes=("bf16",)) as eng:
            (got,) = eng.predict(x)
        assert got.dtype == np.float32  # cast back at the boundary
        np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)
        assert not np.array_equal(got, want)  # really computed in bf16

    def test_bf16_on_stablehlo_artifact_raises(self, artifact):
        _model, prefix = artifact
        with pytest.raises(ValueError, match="StableHLO"):
            serving.Engine(prefix, passes=("bf16",))

    def test_unknown_pass_raises(self, artifact):
        _model, prefix = artifact
        with pytest.raises(ValueError, match="unknown serving pass"):
            serving.Engine(prefix, passes=("fuse_everything",))

    def test_donate_pass_serves_correctly(self, artifact):
        model, prefix = artifact
        x = np.random.RandomState(5).randn(3, 8).astype(np.float32)
        with serving.Engine(prefix, bucket_ladder=(4,),
                            passes=("donate",)) as eng:
            (got,) = eng.predict(x)
        np.testing.assert_array_equal(got, model(Tensor(x)).numpy())

    def test_output_pruning_subset(self, tmp_path):
        """outputs= serves a fetch subset (reference: prune-to-fetch-set);
        unknown names raise with the valid list."""
        paddle.seed(13)

        class TwoHead(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)
                self.a = nn.Linear(8, 4)
                self.b = nn.Linear(8, 2)

            def forward(self, x):
                h = paddle.tanh(self.fc(x))
                return self.a(h), self.b(h)

        model = TwoHead()
        model.eval()
        prefix = str(tmp_path / "two")
        jit_save(model, prefix, input_spec=[InputSpec([None, 8], "float32")])
        x = np.random.RandomState(6).randn(2, 8).astype(np.float32)
        _wa, wb = model(Tensor(x))
        with serving.Engine(prefix, bucket_ladder=(4,),
                            outputs=["output_1"]) as eng:
            assert eng.output_names == ["output_1"]
            outs = eng.predict(x)
        assert len(outs) == 1
        np.testing.assert_array_equal(outs[0], wb.numpy())
        with pytest.raises(ValueError, match="valid output names"):
            serving.Engine(prefix, outputs=["output_9"])

    def test_serving_ladder_twin_registered_and_clean(self):
        from paddle_tpu.analysis import errors, ladder
        assert "serving" in ladder.LADDER_BUILDERS
        findings, summary = ladder.verify_ladder(["serving"])
        assert not findings, [f.message for f in findings]
        assert len(summary["serving"]) == 2  # source + optimized twin


class TestSLOTelemetry:
    def test_percentile_summaries_and_counters_export(self, artifact):
        _model, prefix = artifact
        obs_export.clear_summaries()
        with serving.Engine(prefix, bucket_ladder=(1, 4),
                            batch_timeout_ms=1.0) as eng:
            rng = np.random.RandomState(7)
            for _ in range(12):
                eng.predict(rng.randn(1 + rng.randint(4), 8)
                            .astype(np.float32))
        text = obs_export.prometheus_text()
        assert "# TYPE paddle_tpu_serving_latency_ms summary" in text
        for q in ('quantile="0.5"', 'quantile="0.95"', 'quantile="0.99"'):
            assert f"paddle_tpu_serving_latency_ms{{{q}}}" in text
        assert "paddle_tpu_serving_latency_ms_count" in text
        assert 'paddle_tpu_serving_requests_total{bucket="' in text
        assert "paddle_tpu_serving_batch_fill_ratio" in text
        tele = obs_export.telemetry_dict()
        lat = tele["summaries"]["serving_latency_ms"]
        assert lat["count"] >= 12
        assert lat["p50"] <= lat["p95"] <= lat["p99"]
        assert "serving_queue_wait_ms" in tele["summaries"]
        assert "serving_device_ms" in tele["summaries"]

    def test_empty_summary_serializes_as_valid_json(self):
        """A registered summary with zero observations must not leak the
        invalid-JSON literal NaN into telemetry (strict parsers reject
        it)."""
        import json
        obs_export.clear_summaries()
        obs_export.summary("t_empty")  # get-or-create before any traffic
        try:
            snap = obs_export.summaries()["t_empty"]
            assert snap["p50"] is None and snap["count"] == 0
            text = json.dumps(obs_export.telemetry_dict())
            json.loads(text)  # strict round-trip
            assert "NaN" not in text
        finally:
            obs_export.clear_summaries()

    def test_clear_summaries_keeps_live_engine_exporting(self, artifact):
        """clear_summaries() resets in place: an engine's cached board
        handles must keep exporting afterwards (regression: dropping
        registry entries orphaned live engines' telemetry)."""
        _model, prefix = artifact
        with serving.Engine(prefix, bucket_ladder=(1, 4),
                            batch_timeout_ms=1.0) as eng:
            eng.predict(np.ones((1, 8), np.float32))
            obs_export.clear_summaries()  # mid-life reset
            snap = obs_export.summaries()["serving_latency_ms"]
            assert snap["p50"] is None  # quantile window emptied
            before = snap["count"]  # lifetime count stays monotonic
            eng.predict(np.ones((1, 8), np.float32))
            snap = obs_export.summaries()["serving_latency_ms"]
            assert snap["p50"] is not None  # still wired to the board
            assert snap["count"] == before + 1

    def test_max_batch_size_validated(self, artifact):
        _model, prefix = artifact
        for bad in (0, -3):
            with pytest.raises(ValueError, match="max_batch_size"):
                serving.Engine(prefix, bucket_ladder=(1, 4),
                               max_batch_size=bad)
        with pytest.raises(ValueError, match="exceeds the top bucket"):
            serving.Engine(prefix, bucket_ladder=(1, 4), max_batch_size=9)

    def test_submit_snapshots_caller_buffer(self, artifact):
        """Async contract: mutating the input array after submit() must
        not corrupt the queued request."""
        model, prefix = artifact
        with serving.Engine(prefix, bucket_ladder=(1, 4, 16),
                            batch_timeout_ms=100.0) as eng:
            x = np.random.RandomState(31).randn(2, 8).astype(np.float32)
            want = model(Tensor(x)).numpy()
            fut = eng.submit(x)
            x[:] = 0.0  # caller reuses its buffer while queued
            (out,) = fut.result(timeout=30)
        np.testing.assert_array_equal(out, want)

    def test_summary_quantiles(self):
        s = obs_export.Summary("t_unit", window=128)
        for v in range(1, 101):
            s.observe(float(v))
        q = s.quantiles()
        assert q[0.5] == pytest.approx(50.5, abs=1.0)
        assert q[0.99] == pytest.approx(100.0, abs=2.0)
        assert s.count == 100 and s.sum == pytest.approx(5050.0)

    def test_serving_spans_recorded(self, artifact, tmp_path):
        import json

        import paddle_tpu.observability as obs
        _model, prefix = artifact
        obs.enable(categories=["serving"])
        try:
            from paddle_tpu import profiler
            profiler.reset()
            with serving.Engine(prefix, bucket_ladder=(2,),
                                batch_timeout_ms=1.0) as eng:
                eng.predict(np.ones((1, 8), np.float32))
            trace = str(tmp_path / "trace.json")
            obs.export_chrome_trace(trace)
        finally:
            obs.disable()
        with open(trace) as f:
            names = {e["name"] for e in json.load(f)["traceEvents"]}
        assert "serving/aot_compile" in names
        assert "serving/device_step" in names
        assert "serving/queue_wait" in names
        assert "serving/pad" in names


class TestPredictorDelegation:
    def test_config_enable_serving_engine(self, artifact):
        model, prefix = artifact
        cfg = Config(prefix + ".pdmodel", prefix + ".pdiparams")
        cfg.enable_serving_engine(bucket_ladder=(1, 4), batch_timeout_ms=1.0)
        pred = create_predictor(cfg)
        x = np.random.RandomState(8).randn(3, 8).astype(np.float32)
        pred.get_input_handle("feat").copy_from_cpu(x)
        outs = pred.run()
        np.testing.assert_array_equal(outs[0], model(Tensor(x)).numpy())
        assert pred._engine.stats()["requests"] == 1
        out = pred.get_output_handle("output_0").copy_to_cpu()
        np.testing.assert_array_equal(out, outs[0])
        pred.close()
        assert pred._engine is None  # engine released, thread joined

    def test_delegation_with_output_subset(self, tmp_path):
        """An outputs= subset on the delegated engine must re-map the
        predictor's output names too (regression: get_output_handle used
        to index the stale full-name list into the pruned results)."""
        paddle.seed(14)

        class TwoHead(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(8, 4)
                self.b = nn.Linear(8, 2)

            def forward(self, x):
                return self.a(x), self.b(x)

        model = TwoHead()
        model.eval()
        prefix = str(tmp_path / "two")
        jit_save(model, prefix, input_spec=[InputSpec([None, 8], "float32")])
        cfg = Config(prefix + ".pdmodel", prefix + ".pdiparams")
        cfg.enable_serving_engine(bucket_ladder=(4,), batch_timeout_ms=1.0,
                                  outputs=["output_1"])
        pred = create_predictor(cfg)
        assert pred.get_output_names() == ["output_1"]
        x = np.random.RandomState(9).randn(2, 8).astype(np.float32)
        pred.get_input_handle(pred.get_input_names()[0]).copy_from_cpu(x)
        pred.run()
        _wa, wb = model(Tensor(x))
        out = pred.get_output_handle("output_1").copy_to_cpu()
        np.testing.assert_array_equal(out, wb.numpy())
        with pytest.raises(ValueError, match="valid output names"):
            pred.get_output_handle("output_0")  # pruned away
        pred.close()

    def test_as_engine_from_predictor(self, artifact):
        model, prefix = artifact
        pred = create_predictor(Config(prefix + ".pdmodel",
                                       prefix + ".pdiparams"))
        with pred.as_engine(bucket_ladder=(2,),
                            batch_timeout_ms=1.0) as eng:
            x = np.ones((2, 8), np.float32)
            np.testing.assert_array_equal(eng.predict(x)[0],
                                          model(Tensor(x)).numpy())

    def test_as_engine_artifact_ignores_input_specs(self, artifact):
        """input_specs on a StableHLO-backed predictor is redundant: it
        must warn and serve, not crash with an opaque TypeError."""
        _model, prefix = artifact
        pred = create_predictor(Config(prefix + ".pdmodel",
                                       prefix + ".pdiparams"))
        with pytest.warns(UserWarning, match="records its own input"):
            eng = pred.as_engine(
                input_specs=[InputSpec([None, 8], "float32")],
                bucket_ladder=(2,), batch_timeout_ms=1.0)
        with eng:
            assert eng.predict(np.ones((1, 8), np.float32))[0].shape == \
                (1, 4)


class TestInferenceSatellites:
    """Regression tests for the PR-6 inference bugfixes."""

    def test_reshape_declares_and_enforces(self, artifact):
        _model, prefix = artifact
        pred = create_predictor(Config(prefix + ".pdmodel",
                                       prefix + ".pdiparams"))
        h = pred.get_input_handle("feat")
        x = np.ones((3, 8), np.float32)
        h.reshape([3, 8])
        h.copy_from_cpu(x)  # exact match ok
        h.reshape([-1, 8])
        h.copy_from_cpu(x)  # wildcard batch ok
        h.reshape([2, 8])
        with pytest.raises(ValueError, match="declared via reshape"):
            h.copy_from_cpu(x)
        # the declaration persists across handle objects (reference: the
        # reshape sizes the predictor's feed tensor, not a local view)
        with pytest.raises(ValueError, match="declared via reshape"):
            pred.get_input_handle("feat").copy_from_cpu(x)
        with pytest.raises(ValueError, match="declared via reshape"):
            h.copy_from_cpu(np.ones((2, 9), np.float32))

    def test_output_handle_bad_name_lists_valid(self, artifact):
        _model, prefix = artifact
        pred = create_predictor(Config(prefix + ".pdmodel",
                                       prefix + ".pdiparams"))
        with pytest.raises(ValueError, match=r"valid output names: "
                                             r"\['output_0'\]"):
            pred.get_output_handle("logits")

    def test_positional_names_still_work_on_named_artifacts(self, tmp_path):
        """Callers using conventional "output_<i>" names against an
        artifact with custom output names keep working (positional alias
        is unambiguous there); typos still raise with the valid list."""
        from paddle_tpu.jit.export import save_exported
        model = _mlp(seed=15)
        prefix = str(tmp_path / "named")
        sd = model.state_dict()
        save_exported(prefix, model.forward, list(sd.items()),
                      [InputSpec([None, 8], "float32", name="feat")],
                      output_names=["logits"])
        pred = create_predictor(Config(prefix + ".pdmodel",
                                       prefix + ".pdiparams"))
        assert pred.get_output_names() == ["logits"]
        x = np.ones((2, 8), np.float32)
        pred.get_input_handle("feat").copy_from_cpu(x)
        pred.run()
        np.testing.assert_array_equal(
            pred.get_output_handle("output_0").copy_to_cpu(),
            pred.get_output_handle("logits").copy_to_cpu())
        with pytest.raises(ValueError, match="valid output names"):
            pred.get_output_handle("output_1")  # out of range
        with pytest.raises(ValueError, match="valid output names"):
            pred.get_output_handle("logit")  # typo

    def test_results_do_not_alias_batch_buffer(self, artifact):
        """Resolved results must be standalone arrays, not views pinning
        the bucket-sized batch output (and its co-batched rows)."""
        _model, prefix = artifact
        with serving.Engine(prefix, bucket_ladder=(16,),
                            batch_timeout_ms=1.0) as eng:
            (out,) = eng.predict(np.ones((2, 8), np.float32))
        assert out.shape == (2, 4)
        assert out.base is None or out.base.shape == out.shape

    def test_legacy_output_handle_validation(self, tmp_path):
        """Legacy artifact (no recorded output names): malformed names
        raise instead of the old bare int() ValueError."""
        model = nn.Sequential(nn.Linear(4, 4))
        prefix = str(tmp_path / "leg")
        with pytest.warns(UserWarning, match="input_spec"):
            jit_save(model, prefix)
        pred = create_predictor(Config(prefix))
        with pytest.raises(ValueError, match="valid output names"):
            pred.get_output_handle("fetch/0")
        pred.run([np.ones((2, 4), np.float32)])
        with pytest.raises(ValueError, match="valid output names"):
            pred.get_output_handle("output_3")  # out of range post-run
        out = pred.get_output_handle("output_0").copy_to_cpu()
        assert out.shape == (2, 4)

    def test_bench_err_not_in_repo(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        assert not os.path.exists(os.path.join(repo, "bench.err"))
        with open(os.path.join(repo, ".gitignore")) as f:
            assert "*.err" in f.read()
