"""Round-2 op-breadth tail: math extras, loss tail, spatial/vision ops,
decoding/CRF/sampled-softmax, segment pool. Numpy-reference checks plus
spot grad checks through the tape."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F
import paddle_tpu.incubate as incubate
from paddle_tpu.ops import sequence as seq
from paddle_tpu.core.tensor import Tensor

rng = np.random.RandomState(7)


def t(a):
    return paddle.to_tensor(np.asarray(a))


class TestMathTail:
    def test_gamma_funcs(self):
        x = t(np.array([0.5, 1.0, 2.5], np.float32))
        from scipy import special as sp  # scipy is available with jax
        np.testing.assert_allclose(paddle.digamma(x).numpy(),
                                   sp.digamma([0.5, 1, 2.5]), rtol=1e-5)
        np.testing.assert_allclose(paddle.lgamma(x).numpy(),
                                   sp.gammaln([0.5, 1, 2.5]), rtol=1e-5,
                                   atol=1e-6)

    def test_complex_parts(self):
        x = t(np.array([1 + 2j, 3 - 4j], np.complex64))
        np.testing.assert_allclose(paddle.real(x).numpy(), [1, 3])
        np.testing.assert_allclose(paddle.imag(x).numpy(), [2, -4])
        np.testing.assert_allclose(paddle.conj(x).numpy(), [1 - 2j, 3 + 4j])

    def test_mv_dist_increment(self):
        m = rng.rand(3, 4).astype(np.float32)
        v = rng.rand(4).astype(np.float32)
        np.testing.assert_allclose(paddle.mv(t(m), t(v)).numpy(), m @ v,
                                   rtol=1e-5)
        a = rng.rand(5).astype(np.float32)
        b = rng.rand(5).astype(np.float32)
        np.testing.assert_allclose(paddle.dist(t(a), t(b), p=2).numpy(),
                                   np.linalg.norm(a - b), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.dist(t(a), t(b), p=float("inf")).numpy(),
            np.abs(a - b).max(), rtol=1e-5)
        np.testing.assert_allclose(paddle.increment(t(a), 2.0).numpy(), a + 2)

    def test_unbind_broadcast_multiplex_crop(self):
        x = rng.rand(2, 3).astype(np.float32)
        parts = paddle.unbind(t(x), axis=1)
        assert len(parts) == 3
        np.testing.assert_allclose(parts[1].numpy(), x[:, 1])
        outs = paddle.broadcast_tensors([t(np.ones((1, 3), np.float32)),
                                         t(np.ones((2, 1), np.float32))])
        assert outs[0].shape == [2, 3] and outs[1].shape == [2, 3]
        sel = paddle.multiplex([t(x), t(x * 10)], t(np.array([1, 0])))
        np.testing.assert_allclose(sel.numpy(), np.stack([x[0] * 10, x[1]]))
        c = paddle.crop(t(x), shape=[1, -1], offsets=[1, 1])
        np.testing.assert_allclose(c.numpy(), x[1:2, 1:])
        np.testing.assert_allclose(
            paddle.ops.extras.squared_l2_norm(t(x)).numpy(),
            (x ** 2).sum(), rtol=1e-5)

    def test_dist_grad(self):
        a = t(rng.rand(4).astype(np.float32))
        a.stop_gradient = False
        loss = paddle.dist(a, t(np.zeros(4, np.float32)), p=2)
        loss.backward()
        np.testing.assert_allclose(
            a.grad.numpy(), a.numpy() / np.linalg.norm(a.numpy()), rtol=1e-4)


class TestLossTail:
    def test_rank_and_margin_rank(self):
        lab = t(np.array([[1.0], [0.0]], np.float32))
        l = t(np.array([[0.5], [0.2]], np.float32))
        r = t(np.array([[0.3], [0.6]], np.float32))
        o = (l.numpy() - r.numpy())
        want = -lab.numpy() * o + np.log1p(np.exp(o))
        np.testing.assert_allclose(F.rank_loss(lab, l, r).numpy(), want,
                                   rtol=1e-5)
        want2 = np.maximum(0, -lab.numpy() * o + 0.1)
        np.testing.assert_allclose(
            F.margin_rank_loss(lab, l, r, margin=0.1).numpy(), want2,
            rtol=1e-5)

    def test_huber_matches_reference_example(self):
        x = t(np.array([[1.], [2.], [3.], [4.]], np.float32))
        y = t(np.array([[3.], [3.], [4.], [4.]], np.float32))
        np.testing.assert_allclose(
            F.huber_loss(x, y, 1.0).numpy().ravel(), [1.5, 0.5, 0.5, 0.0])

    def test_log_loss(self):
        p = t(np.array([[0.9], [0.1]], np.float32))
        lab = t(np.array([[1.0], [0.0]], np.float32))
        want = -np.log(np.array([0.9, 0.9]) + 1e-4)
        np.testing.assert_allclose(F.log_loss(p, lab).numpy().ravel(), want,
                                   rtol=1e-4)

    def test_bpr_loss_reference_formula(self):
        x = rng.randn(3, 5).astype(np.float32)
        lab = np.array([[0], [2], [4]])
        got = F.bpr_loss(t(x), t(lab)).numpy().ravel()

        def sig(z):
            return 1 / (1 + np.exp(-z))

        want = []
        for i in range(3):
            li = lab[i, 0]
            s = [np.log(sig(x[i, li] - x[i, j])) for j in range(5) if j != li]
            want.append(-np.mean(s))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_npair_center(self):
        a = t(rng.rand(4, 6).astype(np.float32))
        p = t(rng.rand(4, 6).astype(np.float32))
        labels = t(np.array([0, 1, 1, 0]))
        val = float(F.npair_loss(a, p, labels).numpy())
        assert np.isfinite(val) and val > 0
        centers = t(np.zeros((3, 6), np.float32))
        centers._mark_stateful()
        loss = F.center_loss(a, t(np.array([0, 1, 2, 0])), 3, 0.5, centers)
        assert loss.shape == [4, 1]
        assert np.abs(centers.numpy()).sum() > 0  # centers moved

    def test_nce_and_sampled_softmax(self):
        x = t(rng.rand(4, 8).astype(np.float32))
        x.stop_gradient = False
        w = t(rng.rand(50, 8).astype(np.float32))
        lab = t(np.array([[3], [10], [20], [49]]))
        loss = F.nce(x, lab, w, None, 50, 5).sum()
        loss.backward()
        assert x.grad is not None and np.isfinite(x.grad.numpy()).all()
        ssce = F.sampled_softmax_with_cross_entropy(
            t(rng.randn(4, 50).astype(np.float32)), lab, 10)
        assert ssce.shape == [4, 1]
        assert (ssce.numpy() > 0).all()


class TestSpatial:
    def test_affine_grid_sample_identity(self):
        x = t(rng.rand(2, 3, 4, 5).astype(np.float32))
        theta = t(np.tile(np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32),
                          (2, 1, 1)))
        g = F.affine_grid(theta, [2, 3, 4, 5])
        y = F.grid_sample(x, g)
        np.testing.assert_allclose(y.numpy(), x.numpy(), atol=2e-3)

    def test_grid_sample_padding_modes(self):
        x = t(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
        g = t(np.array([[[[-2.0, -2.0]]]], np.float32))  # out of range
        z = F.grid_sample(x, g, padding_mode="zeros")
        assert z.numpy().ravel()[0] == 0.0
        b = F.grid_sample(x, g, padding_mode="border")
        assert b.numpy().ravel()[0] == 0.0  # clamps to top-left corner value 0

    def test_grid_sample_grad(self):
        x = t(rng.rand(1, 2, 3, 3).astype(np.float32))
        x.stop_gradient = False
        theta = t(np.array([[[0.8, 0, 0.1], [0, 0.8, -0.1]]], np.float32))
        g = F.affine_grid(theta, [1, 2, 3, 3])
        F.grid_sample(x, g).sum().backward()
        assert x.grad is not None and np.isfinite(x.grad.numpy()).all()

    def test_channel_ops(self):
        cs = F.channel_shuffle(
            t(np.arange(8, dtype=np.float32).reshape(1, 8, 1, 1)), 2)
        np.testing.assert_allclose(cs.numpy().ravel(),
                                   [0, 4, 1, 5, 2, 6, 3, 7])
        s2d = F.space_to_depth(
            t(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)), 2)
        assert s2d.shape == [1, 4, 2, 2]
        x = t(rng.rand(2, 3, 4, 5).astype(np.float32))
        ac = F.affine_channel(x, t(np.full(3, 2.0, np.float32)),
                              t(np.ones(3, np.float32)))
        np.testing.assert_allclose(ac.numpy(), 2 * x.numpy() + 1, rtol=1e-6)
        ts = F.temporal_shift(t(rng.rand(4, 8, 2, 2).astype(np.float32)), 2)
        assert ts.shape == [4, 8, 2, 2]
        l = F.local_response_norm(x)
        assert l.shape == x.shape

    def test_deformable_conv_zero_offset_equals_conv(self):
        import jax
        import jax.numpy as jnp
        xx = rng.rand(1, 4, 6, 6).astype(np.float32)
        w = rng.rand(5, 4, 3, 3).astype(np.float32)
        off = np.zeros((1, 18, 4, 4), np.float32)
        dc = F.deformable_conv(t(xx), t(off), t(w))
        ref = jax.lax.conv_general_dilated(jnp.asarray(xx), jnp.asarray(w),
                                           (1, 1), "VALID")
        np.testing.assert_allclose(dc.numpy(), np.asarray(ref), rtol=1e-4,
                                   atol=1e-5)
        # v2: mask of 0.5 halves the output
        m = np.full((1, 9, 4, 4), 0.5, np.float32)
        dc2 = F.deformable_conv(t(xx), t(off), t(w), mask=t(m))
        np.testing.assert_allclose(dc2.numpy(), 0.5 * np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_max_pool_mask_roundtrip(self):
        x = t(rng.rand(2, 3, 6, 6).astype(np.float32))
        out, mask = F.max_pool2d(x, 2, return_mask=True)
        g = np.take_along_axis(x.numpy().reshape(2, 3, 36),
                               mask.numpy().reshape(2, 3, -1),
                               axis=2).reshape(out.shape)
        np.testing.assert_allclose(g, out.numpy())
        up = F.max_unpool2d(out, mask, 2)
        assert up.shape == [2, 3, 6, 6]
        assert int((up.numpy() != 0).sum()) <= 2 * 3 * 9

    def test_roi_pool(self):
        from paddle_tpu.vision.ops import roi_pool
        feat = t(np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6))
        boxes = t(np.array([[0, 0, 5, 5], [2, 2, 4, 4]], np.float32))
        bn = t(np.array([2], np.int32))
        out = roi_pool(feat, boxes, bn, 2)
        np.testing.assert_allclose(out.numpy()[0, 0],
                                   [[14, 17], [32, 35]])


class TestDecoding:
    def test_gather_tree_reference_example(self):
        ids = t(np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]],
                          [[0, 1], [9, 0]]], np.int64))
        par = t(np.array([[[0, 0], [1, 1]], [[1, 0], [1, 0]],
                          [[0, 0], [0, 1]]], np.int64))
        out = seq.gather_tree(ids, par).numpy()
        want = [[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]]
        np.testing.assert_array_equal(out, want)

    def test_edit_distance(self):
        a = t(np.array([[1, 2, 3, 4], [1, 1, 0, 0]], np.int64))
        b = t(np.array([[1, 3, 3, 0], [1, 1, 0, 0]], np.int64))
        d, n = seq.edit_distance(
            a, b, normalized=False,
            input_length=t(np.array([4, 2])), label_length=t(np.array([3, 2])))
        np.testing.assert_allclose(d.numpy().ravel(), [2.0, 0.0])
        dn, _ = seq.edit_distance(
            a, b, normalized=True,
            input_length=t(np.array([4, 2])), label_length=t(np.array([3, 2])))
        np.testing.assert_allclose(dn.numpy().ravel(), [2 / 3, 0.0],
                                   rtol=1e-6)

    def test_ctc_align(self):
        x = t(np.array([[0, 1, 1, 0, 2, 2, 0, 3]], np.int64))
        al, ln = seq.ctc_align(x)
        np.testing.assert_array_equal(al.numpy()[0][:3], [1, 2, 3])
        assert int(ln.numpy()[0]) == 3

    def test_row_conv(self):
        out = seq.row_conv(t(np.ones((1, 4, 2), np.float32)),
                           t(np.ones((2, 2), np.float32)))
        np.testing.assert_allclose(out.numpy()[0, :, 0], [2, 2, 2, 1])

    def test_linear_chain_crf_brute_force(self):
        import itertools
        B, T, N = 2, 4, 3
        emis = rng.randn(B, T, N).astype(np.float32)
        trans = rng.randn(N + 2, N).astype(np.float32)
        lab = rng.randint(0, N, (B, T)).astype(np.int64)
        lens = np.array([4, 3])
        from paddle_tpu.text import linear_chain_crf, crf_decoding
        ll = linear_chain_crf(t(emis), t(lab), t(trans), t(lens)).numpy()

        def score(e, path):
            s = trans[0, path[0]] + e[0, path[0]]
            for i in range(1, len(path)):
                s += trans[2 + path[i - 1], path[i]] + e[i, path[i]]
            return s + trans[1, path[-1]]

        for bi in range(B):
            L = lens[bi]
            allp = list(itertools.product(range(N), repeat=L))
            logz = np.log(sum(np.exp(score(emis[bi], p)) for p in allp))
            # reference returns the NEGATIVE log-likelihood (kernel's -ll)
            want = logz - score(emis[bi], tuple(lab[bi, :L]))
            np.testing.assert_allclose(ll[bi, 0], want, rtol=1e-4)
            best = max(allp, key=lambda p: score(emis[bi], p))
            dec = crf_decoding(t(emis), t(trans), length=t(lens)).numpy()
            np.testing.assert_array_equal(dec[bi, :L], best)

    def test_crf_grad(self):
        emis = t(rng.randn(2, 3, 4).astype(np.float32))
        trans = t(rng.randn(6, 4).astype(np.float32))
        emis.stop_gradient = False
        trans.stop_gradient = False
        from paddle_tpu.text import linear_chain_crf
        lab = t(rng.randint(0, 4, (2, 3)).astype(np.int64))
        linear_chain_crf(emis, lab, trans).sum().backward()
        assert np.isfinite(emis.grad.numpy()).all()
        assert np.isfinite(trans.grad.numpy()).all()


class TestSegment:
    def test_segment_ops(self):
        d = t(np.array([[1.0, 2], [3, 4], [5, 6]], np.float32))
        s = t(np.array([0, 0, 1]))
        np.testing.assert_allclose(incubate.segment_sum(d, s).numpy(),
                                   [[4, 6], [5, 6]])
        np.testing.assert_allclose(incubate.segment_mean(d, s).numpy(),
                                   [[2, 3], [5, 6]])
        np.testing.assert_allclose(incubate.segment_max(d, s).numpy(),
                                   [[3, 4], [5, 6]])
        np.testing.assert_allclose(incubate.segment_min(d, s).numpy(),
                                   [[1, 2], [5, 6]])

    def test_segment_sum_grad(self):
        d = t(np.array([[1.0, 2], [3, 4], [5, 6]], np.float32))
        d.stop_gradient = False
        incubate.segment_sum(d, t(np.array([0, 0, 1]))).sum().backward()
        np.testing.assert_allclose(d.grad.numpy(), np.ones((3, 2)))


class TestDetectionMisc:
    def _yolo_inputs(self):
        from paddle_tpu.vision.ops import yolov3_loss
        N, H, W, C = 2, 4, 4, 3
        mask = [0, 1]
        anchors = [10, 13, 16, 30, 33, 23]
        x = t((rng.randn(N, len(mask) * (5 + C), H, W) * 0.1)
              .astype(np.float32))
        gtb = t(np.array([[[.3, .3, .2, .2], [.7, .6, .3, .4]],
                          [[.5, .5, .4, .3], [0, 0, 0, 0]]], np.float32))
        gtl = t(np.array([[0, 2], [1, 0]], np.int64))
        return yolov3_loss, x, gtb, gtl, anchors, mask, C, N

    def test_yolov3_loss_forward(self):
        fn, x, gtb, gtl, anchors, mask, C, N = self._yolo_inputs()
        loss = fn(x, gtb, gtl, anchors, mask, C, 0.7, 8)
        assert loss.shape == [N]
        assert (loss.numpy() > 0).all()
        # mixup scores scale the positive losses
        gts = t(np.array([[0.5, 0.5], [0.5, 0.5]], np.float32))
        loss2 = fn(x, gtb, gtl, anchors, mask, C, 0.7, 8, gt_score=gts)
        assert (loss2.numpy() <= loss.numpy() + 1e-5).all()

    @pytest.mark.slow  # ~12 s: the XLA grad compile of the full yolo
    # loss dominates; the forward contract stays tier-1 just above
    def test_yolov3_loss_grad(self):
        fn, x, gtb, gtl, anchors, mask, C, N = self._yolo_inputs()
        x.stop_gradient = False
        loss = fn(x, gtb, gtl, anchors, mask, C, 0.7, 8)
        loss.sum().backward()
        g = x.grad.numpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_anchor_generator(self):
        from paddle_tpu.vision.ops import anchor_generator
        a, v = anchor_generator(t(np.zeros((1, 8, 2, 3), np.float32)),
                                [64.0], [1.0], [16.0, 16.0])
        assert a.shape == [2, 3, 1, 4] and v.shape == [2, 3, 1, 4]
        an = a.numpy()
        # centers advance by the stride
        np.testing.assert_allclose(an[0, 1, 0, 0] - an[0, 0, 0, 0], 16.0)
        np.testing.assert_allclose(an[1, 0, 0, 1] - an[0, 0, 0, 1], 16.0)
        np.testing.assert_allclose(v.numpy()[0, 0, 0], [0.1, 0.1, 0.2, 0.2])

    def test_cvm(self):
        x = t(np.array([[3.0, 1, 5, 6], [7, 0, 1, 2]], np.float32))
        out = paddle.cvm(x)
        np.testing.assert_allclose(out.numpy()[0, 0], np.log(4.0), rtol=1e-6)
        np.testing.assert_allclose(out.numpy()[0, 1],
                                   np.log(2.0) - np.log(4.0), rtol=1e-5)
        np.testing.assert_allclose(out.numpy()[:, 2:], x.numpy()[:, 2:])
        assert paddle.cvm(x, use_cvm=False).shape == [2, 2]

    def test_data_norm(self):
        from paddle_tpu.ops.extras import data_norm
        x = t(rng.rand(8, 4).astype(np.float32))
        bs = t(np.full(4, 1e4, np.float32))
        bsum = t(np.zeros(4, np.float32))
        bsq = t(np.full(4, 1e4, np.float32))
        for s in (bs, bsum, bsq):
            s._mark_stateful()
        out = data_norm(x, bs, bsum, bsq)
        # mean 0 scale 1 summaries: y = x
        np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-5)
        assert float(bs.numpy()[0]) > 1e4  # stats accumulated


class TestPyFunc:
    def test_py_func_forward_backward(self):
        import paddle_tpu.static as static
        x = t(np.array([1.0, 2.0, 3.0], np.float32))
        x.stop_gradient = False
        spec = static.InputSpec([3], "float32")
        # backward_func receives (inputs, outputs, out-grads)
        y = static.py_func(lambda a: a * 2 + 1, x, spec,
                           backward_func=lambda a, y, g: g * 2)
        np.testing.assert_allclose(y.numpy(), [3, 5, 7])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2, 2, 2])

    def test_py_func_multi_io(self):
        import paddle_tpu.static as static
        a = t(np.ones(2, np.float32))
        b = t(np.full(2, 3.0, np.float32))
        specs = [static.InputSpec([2], "float32"),
                 static.InputSpec([2], "float32")]
        o1, o2 = static.py_func(lambda u, v: (u + v, u * v), [a, b], specs)
        np.testing.assert_allclose(o1.numpy(), [4, 4])
        np.testing.assert_allclose(o2.numpy(), [3, 3])


class TestPoolingEdgeCases:
    def test_max_pool_mask_ceil_mode(self):
        x = t(rng.rand(1, 2, 5, 5).astype(np.float32))
        out, mask = F.max_pool2d(x, 2, stride=2, ceil_mode=True,
                                 return_mask=True)
        ref = F.max_pool2d(x, 2, stride=2, ceil_mode=True)
        assert out.shape == ref.shape == [1, 2, 3, 3]
        np.testing.assert_allclose(out.numpy(), ref.numpy())
        g = np.take_along_axis(x.numpy().reshape(1, 2, 25),
                               mask.numpy().reshape(1, 2, -1),
                               axis=2).reshape(out.shape)
        np.testing.assert_allclose(g, out.numpy())

    def test_max_unpool_padding_output_size(self):
        # reference default output: (in-1)*stride - 2*pad + ksize
        x = t(rng.rand(1, 1, 8, 8).astype(np.float32))
        out, mask = F.max_pool2d(x, 3, stride=2, padding=1, return_mask=True)
        assert out.shape == [1, 1, 4, 4]
        up = F.max_unpool2d(out, mask, 3, stride=2, padding=1)
        assert up.shape == [1, 1, 7, 7]  # (4-1)*2 - 2*1 + 3
        up2 = F.max_unpool2d(out, mask, 3, stride=2, padding=1,
                             output_size=[8, 8])
        assert up2.shape == [1, 1, 8, 8]


class TestHapiTail:
    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "dependencies = []\n"
            "def lenet(num_classes=10):\n"
            "    '''LeNet entry.'''\n"
            "    from paddle_tpu.vision.models import LeNet\n"
            "    return LeNet(num_classes=num_classes)\n")
        assert paddle.hub.list(str(tmp_path)) == ["lenet"]
        assert "LeNet" in paddle.hub.help(str(tmp_path), "lenet")
        m = paddle.hub.load(str(tmp_path), "lenet", num_classes=7)
        out = m(t(np.zeros((1, 1, 28, 28), np.float32)))
        assert out.shape == [1, 7]
        with pytest.raises(RuntimeError):
            paddle.hub.load(str(tmp_path), "lenet", source="github")

    def test_reduce_lr_on_plateau(self):
        from paddle_tpu.hapi.callbacks import ReduceLROnPlateau

        class FakeModel:
            pass

        m = nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        fm = FakeModel()
        fm._optimizer = opt
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                               verbose=0)
        cb.model = fm
        cb.on_epoch_end(0, {"loss": 1.0})
        cb.on_epoch_end(1, {"loss": 1.0})  # no improvement -> wait=1 -> cut
        assert abs(opt.get_lr() - 0.05) < 1e-9

    def test_visualdl_writes_scalars(self, tmp_path):
        from paddle_tpu.hapi.callbacks import VisualDL
        cb = VisualDL(str(tmp_path))
        cb.on_batch_end("train", 0, {"loss": 0.5})
        cb.on_epoch_end(0, {"loss": 0.4})
        body = (tmp_path / "train.tsv").read_text()
        assert "train/loss" in body and "0.5" in body


class TestLossTail2:
    def test_hsigmoid_default_tree(self):
        x = t(rng.randn(4, 8).astype(np.float32))
        x.stop_gradient = False
        w = t((rng.randn(9, 8) * 0.1).astype(np.float32))
        w.stop_gradient = False
        lab = t(np.array([0, 3, 7, 9]))
        loss = F.hsigmoid_loss(x, lab, 10, w)
        assert loss.shape == [4, 1] and (loss.numpy() > 0).all()
        loss.sum().backward()
        assert np.isfinite(x.grad.numpy()).all()
        assert np.isfinite(w.grad.numpy()).all()
        # training decreases the loss
        xv, wv = x.numpy().copy(), w.numpy().copy()
        for _ in range(50):
            x2 = t(xv); x2.stop_gradient = False
            w2 = t(wv); w2.stop_gradient = False
            l2 = F.hsigmoid_loss(x2, lab, 10, w2).sum()
            l2.backward()
            wv = wv - 0.5 * w2.grad.numpy()
        assert float(l2.numpy()) < float(loss.sum().numpy())

    def test_hsigmoid_custom_tree(self):
        x = t(rng.randn(2, 4).astype(np.float32))
        w = t((rng.randn(5, 4) * 0.1).astype(np.float32))
        tbl = t(np.array([[0, 2, -1], [1, 3, 4]], np.int64))
        code = t(np.array([[1, 0, 0], [0, 1, 1]], np.int64))
        loss = F.hsigmoid_loss(x, t(np.array([0, 1])), 6, w,
                               path_table=tbl, path_code=code)
        assert loss.shape == [2, 1] and np.isfinite(loss.numpy()).all()

    def test_teacher_student_sigmoid_loss(self):
        ts = F.teacher_student_sigmoid_loss(
            t(np.array([1.0, 1.0, 1.0, 1.0], np.float32)),
            t(np.array([-2.0, -1.0, 0.3, 1.6], np.float32)))
        base = 1 + np.log1p(np.exp(-1.0))
        want = [base, base - 1, base + base - 0.3, base - 1 + base - 0.6]
        np.testing.assert_allclose(ts.numpy(), want, rtol=1e-5)


class TestDetectionTail:
    def test_iou_similarity(self):
        from paddle_tpu.vision.ops import iou_similarity
        a = t(np.array([[0, 0, 2, 2]], np.float32))
        b = t(np.array([[1, 1, 3, 3], [0, 0, 2, 2]], np.float32))
        np.testing.assert_allclose(iou_similarity(a, b).numpy(),
                                   [[1 / 7, 1.0]], rtol=1e-5)

    def test_box_clip(self):
        from paddle_tpu.vision.ops import box_clip
        out = box_clip(t(np.array([[-1, -1, 5, 9]], np.float32)),
                       t(np.array([5.0, 5.0, 1.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [[0, 0, 4, 4]])

    def test_fsp_matrix(self):
        f = paddle.ops.extras.fsp_matrix(
            t(np.ones((1, 2, 2, 2), np.float32)),
            t(np.ones((1, 3, 2, 2), np.float32)))
        assert f.shape == [1, 2, 3]
        np.testing.assert_allclose(f.numpy(), np.ones((1, 2, 3)))

    def test_softmax_mask_fuse(self):
        x = t(rng.randn(1, 2, 3, 3).astype(np.float32))
        m = np.zeros((1, 1, 3, 3), np.float32)
        m[..., 2] = -1e9  # mask out last key
        out = incubate.softmax_mask_fuse(x, t(m)).numpy()
        np.testing.assert_allclose(out.sum(-1), np.ones((1, 2, 3)), rtol=1e-5)
        assert (out[..., 2] < 1e-6).all()


class TestEvalCallbacks:
    def test_evaluate_fires_eval_hooks(self, tmp_path):
        from paddle_tpu.hapi.callbacks import VisualDL
        model = paddle.Model(nn.Linear(4, 2))
        model.prepare(loss=nn.CrossEntropyLoss())
        xs = np.random.RandomState(0).rand(8, 4).astype(np.float32)
        ys = np.random.RandomState(0).randint(0, 2, (8,)).astype(np.int64)
        data = [(xs[i], ys[i]) for i in range(8)]
        cb = VisualDL(str(tmp_path))
        out = model.evaluate(data, batch_size=4, callbacks=[cb])
        assert "loss" in out
        body = (tmp_path / "eval.tsv").read_text()
        assert "eval/loss" in body


class TestSequenceTail2:
    def test_hinge_loss(self):
        out = F.hinge_loss(t(np.array([0.5, -0.5, 2.0], np.float32)),
                           t(np.array([1.0, 0.0, 1.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [0.5, 0.5, 0.0])

    def test_sequence_conv_matches_manual(self):
        x = rng.rand(1, 4, 2).astype(np.float32)
        w = rng.rand(6, 3).astype(np.float32)  # ctx=3 * D=2
        out = seq.sequence_conv(t(x), t(w), 3).numpy()
        # manual: window [t-1, t, t+1] zero-padded
        pad = np.concatenate([np.zeros((1, 1, 2)), x, np.zeros((1, 1, 2))],
                             axis=1)
        cols = np.concatenate([pad[:, i:i + 4] for i in range(3)], axis=-1)
        np.testing.assert_allclose(out, cols @ w, rtol=1e-5)

    def test_sequence_reshape_scatter_im2sequence(self):
        x = t(np.arange(12, dtype=np.float32).reshape(1, 2, 6))
        r = seq.sequence_reshape(x, 4)
        assert r.shape == [1, 3, 4]
        np.testing.assert_allclose(r.numpy().ravel(), np.arange(12))
        sx = seq.sequence_scatter(
            t(np.zeros((2, 6), np.float32)),
            t(np.array([[1, 2], [0, 5]])), t(np.ones((2, 2), np.float32)))
        assert sx.numpy()[0, 1] == 1 and sx.numpy()[1, 5] == 1
        patches = seq.im2sequence(
            t(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)), 2, 2)
        assert patches.shape == [4, 4]
        np.testing.assert_allclose(patches.numpy()[0], [0, 1, 4, 5])

    def test_partial_concat_sum(self):
        a = t(np.array([[1.0, 2, 3, 4]], np.float32))
        b = t(np.array([[10.0, 20, 30, 40]], np.float32))
        np.testing.assert_allclose(
            paddle.partial_concat([a, b], 1, 2).numpy(), [[2, 3, 20, 30]])
        np.testing.assert_allclose(
            paddle.partial_sum([a, b], 1, 2).numpy(), [[22, 33]])

    def test_prroi_pool(self):
        from paddle_tpu.vision.ops import prroi_pool
        feat = t(np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6))
        out = prroi_pool(feat, t(np.array([[0, 0, 5, 5]], np.float32)),
                         t(np.array([1], np.int32)), 2)
        assert out.shape == [1, 1, 2, 2]
        # integral-average of a linear ramp: bin centers
        v = out.numpy()[0, 0]
        assert v[0, 0] < v[0, 1] < v[1, 1]

    def test_sequence_conv_positive_context_start(self):
        # look-ahead window: out[t] = x[t+1] (ctx=1, start=1)
        x = np.arange(8, dtype=np.float32).reshape(1, 4, 2)
        w = np.eye(2, dtype=np.float32)
        out = seq.sequence_conv(t(x), t(w), 1, context_start=1).numpy()
        want = np.concatenate([x[:, 1:], np.zeros((1, 1, 2))], axis=1)
        np.testing.assert_allclose(out, want)

    def test_partial_ops_negative_start(self):
        a = t(np.array([[1.0, 2, 3, 4]], np.float32))
        b = t(np.array([[10.0, 20, 30, 40]], np.float32))
        np.testing.assert_allclose(
            paddle.partial_concat([a, b], -1, 1).numpy(), [[4, 40]])
        np.testing.assert_allclose(
            paddle.partial_sum([a, b], -2, 2).numpy(), [[33, 44]])
