"""Multi-process distributed execution (reference: `test_dist_base.py:744`
TestDistBase — spawn real processes on localhost, collect stdout losses,
assert local-vs-distributed loss parity; plus `spawn.py:333`).

These are REAL multi-process tests: each worker runs in its own Python
process with its own XLA runtime, joined through the JAX coordination
service; collectives cross process boundaries (Gloo on the CPU backend).
"""
import os
import re
import subprocess
import sys

import numpy as np
import pytest

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "dist_parity_fixture.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_cross_process_collectives():
    """jaxlib < 0.5 has no cross-process collectives on the CPU backend
    ("Multiprocess computations aren't implemented on the CPU backend" in
    every worker) — the documented known-unfixable gap in this container
    (.claude/skills/verify/SKILL.md). Skip instead of burning ~40 s of
    subprocess startup per tier-1 run on guaranteed failures; these
    re-arm automatically on a jax upgrade or a real accelerator."""
    import jax
    ver = tuple(int(x) for x in jax.__version__.split(".")[:2])
    return ver >= (0, 5)


needs_cross_process = pytest.mark.skipif(
    not _cpu_cross_process_collectives(),
    reason="jaxlib<0.5 CPU backend has no cross-process collectives "
           "(known env gap, see verify SKILL.md)")


def _clean_env():
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("PADDLE_", "JAX_")) or k == "XLA_FLAGS":
            env.pop(k)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _losses(text):
    return [float(m.group(2)) for m in
            re.finditer(r"LOSS (\d+) ([\d.eE+-]+)", text)]


def _run_single():
    env = _clean_env()
    env["JAX_PLATFORMS"] = "cpu"
    script = (
        "import jax; jax.config.update('jax_platforms','cpu');"
        "import runpy; runpy.run_path(%r, run_name='__main__')" % FIXTURE)
    r = subprocess.run([sys.executable, "-c", script], capture_output=True,
                       text=True, env=env, timeout=600, cwd=REPO)
    assert r.returncode == 0, r.stderr[-3000:]
    return _losses(r.stdout)


def _run_launcher(nproc, log_dir, mode="dp", port="19850", host_devices=1):
    env = _clean_env()
    env["DIST_FIXTURE_MODE"] = mode
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", str(nproc), "--started_port", port,
         "--host_devices", str(host_devices), "--log_dir", str(log_dir),
         FIXTURE],
        capture_output=True, text=True, env=env, timeout=600, cwd=REPO)
    assert r.returncode == 0, (r.stderr[-2000:] or "") + _tail_logs(log_dir)
    with open(os.path.join(log_dir, "workerlog.0")) as f:
        return _losses(f.read())


def _tail_logs(log_dir):
    out = []
    try:
        for name in sorted(os.listdir(log_dir)):
            with open(os.path.join(log_dir, name)) as f:
                out.append(f"--- {name} ---\n" + f.read()[-2000:])
    except OSError:
        pass
    return "\n".join(out)


@needs_cross_process
class TestDistLossParity:
    """The reference's headline distributed test: same model, same data,
    1 process vs N processes — losses must match."""

    def test_two_proc_matches_single(self, tmp_path):
        single = _run_single()
        dist2 = _run_launcher(2, str(tmp_path))
        assert len(single) == len(dist2) == 5
        np.testing.assert_allclose(single, dist2, rtol=1e-4, atol=1e-6)

    def test_two_proc_tensor_parallel_matches_single(self, tmp_path):
        """Megatron-sharded weights across two real processes: GSPMD
        collectives cross the process boundary; losses must match the
        unsharded single-process run."""
        single = _run_single()
        mp2 = _run_launcher(2, str(tmp_path), mode="mp", port="19890")
        assert len(mp2) == 5
        np.testing.assert_allclose(single, mp2, rtol=1e-4, atol=1e-6)

    def test_two_proc_four_dev_hybrid_matches_single(self, tmp_path):
        """Multi-host hybrid mesh: 2 processes x 4 virtual devices = 8
        global devices, dp across the process boundary (DCN analog) and
        megatron mp within each process (ICI analog). Loss parity vs one
        process, one device."""
        single = _run_single()
        hyb = _run_launcher(2, str(tmp_path), mode="hybrid", port="19930",
                            host_devices=4)
        assert len(hyb) == 5
        np.testing.assert_allclose(single, hyb, rtol=1e-4, atol=1e-6)


def _spawn_worker(scale):
    """Module-level so the spawn context can pickle it."""
    import jax
    import jax.numpy as jnp
    assert jax.process_count() == 2
    out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
        jnp.ones((jax.local_device_count(),)) * scale * (jax.process_index() + 1))
    return float(np.asarray(out)[0])


class TestSpawn:
    @needs_cross_process
    def test_spawn_two_processes_collective(self):
        from paddle_tpu.distributed.spawn import spawn
        ctx = spawn(_spawn_worker, args=(2.0,), nprocs=2, backend="cpu",
                    devices_per_proc=1, timeout=300)
        results = [payload for _, status, payload in ctx.results]
        # psum over both processes: 2*1 + 2*2 = 6 on every rank
        assert results == [6.0, 6.0]

    def test_spawn_single_inprocess(self):
        from paddle_tpu.distributed.spawn import spawn
        ctx = spawn(lambda: 41 + 1, nprocs=1)
        assert ctx.results[0][2] == 42

    def test_spawn_propagates_worker_failure(self):
        from paddle_tpu.distributed.spawn import spawn
        with pytest.raises(RuntimeError, match="rank"):
            spawn(_failing_worker, nprocs=2, backend="cpu", timeout=300)


def _failing_worker():
    raise ValueError("intentional fixture failure")


def _elastic_worker(root, endpoint, die):
    """Register in a shared FileKVStore from a real process; rank comes from
    live membership (reference elastic.py re-rank semantics)."""
    import time
    from paddle_tpu.distributed.fleet.elastic import ElasticManager, \
        FileKVStore
    mgr = ElasticManager(endpoint, np=2, job_id="mp_elastic",
                         store=FileKVStore(root), ttl=3,
                         heartbeat_interval=0.5)
    mgr.register()
    assert mgr.wait_ready(timeout=60)
    r = mgr.rank()
    deadline = time.time() + 60
    if die:
        # rendezvous: don't leave before the survivor has seen us, or the
        # membership change races the survivor's wait_ready
        while time.time() < deadline and mgr.store.get("survivor_saw") is None:
            time.sleep(0.1)
        mgr.exit()  # leaves the membership; lease is gone
        return r
    mgr.store.put("survivor_saw", "1")
    # survivor: wait for the peer to drop out, then re-rank
    while time.time() < deadline and len(mgr.live_nodes()) > 1:
        time.sleep(0.2)
    out = (r, mgr.rank(), len(mgr.live_nodes()))
    mgr.exit()
    return out


class TestElasticAcrossProcesses:
    def test_rerank_after_member_death(self, tmp_path):
        """Two real processes register; one exits; the survivor re-ranks to
        0 — the reference ElasticManager.watch:316 membership behavior,
        exercised across actual process boundaries."""
        import multiprocessing
        ctx = multiprocessing.get_context("spawn")
        root = str(tmp_path)
        with ctx.Pool(2) as pool:
            dead = pool.apply_async(_elastic_worker,
                                    (root, "127.0.0.1:7001", True))
            live = pool.apply_async(_elastic_worker,
                                    (root, "127.0.0.1:7002", False))
            dead_rank = dead.get(timeout=120)
            initial_rank, final_rank, n_live = live.get(timeout=120)
        assert sorted([dead_rank, initial_rank]) == [0, 1]
        assert n_live == 1
        assert final_rank == 0  # survivor re-ranked to 0


@needs_cross_process
class TestEagerCollectives:
    """Eager (non-shard_map) collectives across REAL processes: formerly
    silent identities, now true cross-process ops (reference:
    collective.py broadcast:348/all_reduce:415 work eagerly in dygraph)."""

    def test_two_proc_eager_collectives(self, tmp_path):
        env = _clean_env()
        env["JAX_PLATFORMS"] = "cpu"
        fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                               "eager_collective_fixture.py")
        log_dir = str(tmp_path)
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--started_port", "19970",
             "--log_dir", log_dir, fixture],
            capture_output=True, text=True, env=env, timeout=600, cwd=REPO)
        assert r.returncode == 0, (r.stderr[-2000:] or "") + _tail_logs(log_dir)
        outs = []
        for i in (0, 1):
            with open(os.path.join(log_dir, f"workerlog.{i}")) as f:
                outs.append(f.read())
        for i, out in enumerate(outs):
            # sum over ranks: (1) + (2) = 3 on BOTH ranks
            assert "CHECK allreduce [3.0, 3.0, 3.0]" in out, out[-1500:]
            # broadcast from rank 1: value 10 everywhere
            assert "CHECK broadcast [10.0, 10.0]" in out, out[-1500:]
            assert "CHECK allgather [5.0, 6.0]" in out, out[-1500:]
            # subgroup [0]: rank0 reduces over itself (1.0), rank1 untouched
            want = 1.0 if i == 0 else 2.0
            assert f"CHECK subgroup {want}" in out, out[-1500:]
            assert "CHECK barrier done" in out
            assert "CHECK send raises" in out
