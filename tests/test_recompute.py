"""Activation recompute + host offload (ISSUE 13).

The policy surface (``paddle_tpu.recompute``) must trade memory for
recompute WITHOUT changing the math: remat'd training is bitwise-equal
(fp32) / tolerance-equal (bf16+master) to its non-remat control across
the sharding matrix zero{0,1,3} x k{1,4} x accumulate_steps{1,2},
including dropout models (the RecomputeFunction RNG-replay contract —
masks replay bitwise because the key mathematics threads through the
remat region). Plus: the policy resolution rules (offload falls back
LOUDLY without a pinned_host memory space), segment constraints,
mutated-state threading (BN running stats, scoped keys), the
jaxpr-liveness meter that carries the bench claim, and the analysis
integrations (remat ladder twin, remat-replay-aware verifier, the
raw-remat-outside-policy lint rule, mem_view --diff).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import recompute as rc
from paddle_tpu.core import random as core_random
from paddle_tpu.distributed import parallel_env

DP = 8


@pytest.fixture(autouse=True)
def _mesh():
    mesh = parallel_env.make_mesh({"dp": DP})
    parallel_env.set_mesh(mesh)
    yield mesh
    parallel_env.set_mesh(None)


rng = np.random.RandomState(7)


def _drop_mlp(bf16=False):
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Dropout(0.25),
                      nn.Linear(32, 8))
    if bf16:
        m.to("bfloat16")
    m.train()
    return m


def _build(remat, zero, k, acc, bf16=False, policy="full", seed=11):
    paddle.seed(seed)
    m = _drop_mlp(bf16)
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 learning_rate=0.05,
                                 multi_precision=bf16)
    if zero:
        opt._zero_enable(axis="dp", stage=zero)
    if remat:
        m.enable_recompute(policy)

    def one(xb, yb):
        loss = nn.functional.cross_entropy(m(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(one, scan_steps=k, dp_axis="dp",
                                accumulate_steps=acc if acc > 1 else None)
    return step, m


def _batches(k, batch=16):
    # deterministic per shape: the control and its remat twin must see
    # the SAME data (a shared module RNG would hand them different draws)
    r = np.random.RandomState(1000 + k)
    x = r.rand(k, batch, 16).astype("float32")
    y = r.randint(0, 8, (k, batch)).astype("int64")
    return paddle.to_tensor(x), paddle.to_tensor(y)


def _run(remat, zero, k, acc, bf16=False, policy="full"):
    step, m = _build(remat, zero, k, acc, bf16=bf16, policy=policy)
    x, y = _batches(k)
    l1 = np.asarray(step(x, y).numpy())
    l2 = np.asarray(step(x, y).numpy())
    params = [np.asarray(p.numpy()) for p in m.parameters()]
    key = np.asarray(paddle.get_rng_state().numpy())
    return l1, l2, params, key


# every (k, acc) shape: k=1 admits only whole-window acc=1
_MATRIX = [(z, k, a) for z in (0, 1, 3) for (k, a) in ((1, 1), (4, 1),
                                                       (4, 2))]
# tier-1 keeps a cheap zero0 k1 case, the windowed zero3 corner, and
# the zero3 acc1 corner (zero{0,3} x acc{1,2} dropout coverage at
# minimum compile cost); zero1 and the remaining product ride the slow
# tier (zero1's machinery is zero_sharding's well-covered middle
# child) — the tier-1 wall-clock budget is tight
_TIER1 = [(0, 1, 1), (3, 4, 2), (3, 4, 1)]
_SLOW = [c for c in _MATRIX if c not in _TIER1]


def _assert_remat_matches(zero, k, acc, bf16=False):
    ref = _run(False, zero, k, acc, bf16=bf16)
    got = _run(True, zero, k, acc, bf16=bf16)
    for a, b, what in [(ref[0], got[0], "losses#1"),
                       (ref[1], got[1], "losses#2")]:
        if bf16:
            np.testing.assert_allclose(
                a.astype(np.float32), b.astype(np.float32), rtol=2e-2,
                atol=2e-2, err_msg=what)
        else:
            assert a.tobytes() == b.tobytes(), \
                f"{what} diverged at zero{zero} k{k} acc{acc}"
    for pa, pb in zip(ref[2], got[2]):
        if bf16:
            np.testing.assert_allclose(pa.astype(np.float32),
                                       pb.astype(np.float32),
                                       rtol=2e-2, atol=2e-2)
        else:
            assert pa.tobytes() == pb.tobytes()
    # the generator advanced identically: remat consumed the RNG stream
    # exactly once per dropout, not once per replay
    assert ref[3].tobytes() == got[3].tobytes()


@pytest.mark.parametrize("zero,k,acc", _TIER1)
def test_remat_bitwise_matches_control_fp32(zero, k, acc):
    """Dropout model under remat == non-remat control, bitwise, through
    the zero/scan/accumulation machinery (RNG replay contract)."""
    _assert_remat_matches(zero, k, acc)


@pytest.mark.slow
@pytest.mark.parametrize("zero,k,acc", _SLOW)
def test_remat_bitwise_matches_control_fp32_full_matrix(zero, k, acc):
    _assert_remat_matches(zero, k, acc)


def test_remat_bf16_master_tolerance():
    _assert_remat_matches(3, 4, 2, bf16=True)


@pytest.mark.slow
def test_remat_bf16_master_tolerance_zero0():
    _assert_remat_matches(0, 4, 2, bf16=True)


@pytest.mark.slow
def test_remat_selective_policy_bitwise():
    _assert_remat_matches_policy("selective")


def _assert_remat_matches_policy(policy):
    ref = _run(False, 3, 4, 2)
    got = _run(True, 3, 4, 2, policy=policy)
    assert ref[0].tobytes() == got[0].tobytes()
    for pa, pb in zip(ref[2], got[2]):
        assert pa.tobytes() == pb.tobytes()


def test_remat_eager_bitwise_with_dropout():
    """Eager remat: ONE tape node for the segment, grads + RNG advance
    bitwise-equal to the plain tape."""
    def run(remat):
        paddle.seed(5)
        m = _drop_mlp()
        if remat:
            m.enable_recompute("full")
        x = paddle.to_tensor(np.random.RandomState(21)
                             .rand(4, 16).astype("float32"))
        x.stop_gradient = False
        loss = m(x).sum()
        loss.backward()
        return (np.asarray(loss.numpy()),
                [np.asarray(p._grad) for p in m.parameters()],
                np.asarray(x._grad),
                np.asarray(paddle.get_rng_state().numpy()))

    ref, got = run(False), run(True)
    assert ref[0].tobytes() == got[0].tobytes()
    for a, b in zip(ref[1], got[1]):
        assert a.tobytes() == b.tobytes()
    assert ref[2].tobytes() == got[2].tobytes()
    assert ref[3].tobytes() == got[3].tobytes()


def test_recompute_wrapper_form_and_fleet_api():
    paddle.seed(3)
    blk = nn.Sequential(nn.Linear(8, 8), nn.ReLU())
    x = paddle.to_tensor(rng.rand(2, 8).astype("float32"))
    wrapped = rc.recompute(blk.forward, policy="selective")
    np.testing.assert_array_equal(np.asarray(wrapped(x).numpy()),
                                  np.asarray(blk(x).numpy()))
    from paddle_tpu.distributed.fleet.utils import recompute as fleet_rc
    np.testing.assert_array_equal(np.asarray(fleet_rc(blk, x).numpy()),
                                  np.asarray(blk(x).numpy()))


# -- policy resolution ------------------------------------------------------

def test_policy_names_and_errors():
    import jax
    fn, name = rc.resolve_policy("full")
    assert name == "full" and fn is jax.checkpoint_policies.nothing_saveable
    fn, name = rc.resolve_policy("selective")
    assert name == "selective"
    assert rc.resolve_policy("none") == (None, "none")
    with pytest.raises(ValueError, match="unknown recompute policy"):
        rc.resolve_policy("bogus")
    with pytest.raises(ValueError):
        nn.Linear(2, 2).enable_recompute("bogus")
    # raw jax policies pass through (the power-user escape hatch)
    fn, name = rc.resolve_policy(jax.checkpoint_policies.dots_saveable)
    assert fn is jax.checkpoint_policies.dots_saveable


def test_offload_falls_back_loudly_on_cpu():
    assert rc.host_offload_available() is False  # CPU: unpinned_host only
    with pytest.warns(UserWarning, match="pinned_host"):
        fn, name = rc.resolve_policy("offload")
    assert name == "selective"  # loud fallback, not a silent no-op
    with pytest.raises(RuntimeError, match="pinned_host"):
        rc.resolve_policy("offload", strict=True)


def test_offload_policy_trains_with_fallback():
    with pytest.warns(UserWarning, match="pinned_host"):
        got = _run(True, 0, 1, 1, policy="offload")
    ref = _run(False, 0, 1, 1)
    assert ref[0].tobytes() == got[0].tobytes()


# -- segment constraints + state threading ----------------------------------

def test_backward_inside_segment_rejected():
    m = nn.Linear(4, 4)

    def seg(x):
        loss = m(x).sum()
        loss.backward()
        return loss

    x = paddle.to_tensor(rng.rand(2, 4).astype("float32"))
    with pytest.raises(RuntimeError, match="forward-only"):
        rc.recompute(seg, x)


def test_new_state_inside_segment_rejected():
    def seg(x):
        p = paddle.Parameter(np.ones((2, 2), np.float32))
        return x @ p

    x = paddle.to_tensor(rng.rand(2, 2).astype("float32"))
    x.stop_gradient = False
    with pytest.raises(RuntimeError, match="NEW framework state"):
        rc.recompute(seg, x)


def test_batchnorm_buffers_advance_exactly_once():
    """Mutated buffers thread through the remat segment: running stats
    advance one run's worth and match the non-remat control."""
    def run(remat):
        paddle.seed(9)
        m = nn.Sequential(nn.Linear(8, 8), nn.BatchNorm1D(8), nn.ReLU())
        m.train()
        if remat:
            m.enable_recompute("full")
        x = paddle.to_tensor(np.random.RandomState(22)
                             .rand(4, 8).astype("float32"))
        x.stop_gradient = False
        loss = m(x).sum()
        loss.backward()
        bn = m[1]
        return (np.asarray(loss.numpy()),
                np.asarray(bn._mean.numpy()),
                np.asarray(bn._variance.numpy()),
                [np.asarray(p._grad) for p in m.parameters()])

    ref, got = run(False), run(True)
    assert ref[0].tobytes() == got[0].tobytes()
    assert ref[1].tobytes() == got[1].tobytes()
    assert ref[2].tobytes() == got[2].tobytes()
    for a, b in zip(ref[3], got[3]):
        assert a.tobytes() == b.tobytes()


def test_scoped_key_replays_from_same_origin():
    """recompute inside a scoped_key block draws the same deterministic
    keys as the plain run AND leaves the counter where the plain run
    would."""
    import jax

    def seg(x):
        h = nn.functional.dropout(x, p=0.5, training=True)
        return nn.functional.dropout(h, p=0.5, training=True)

    x = paddle.to_tensor(np.ones((64,), np.float32))
    x.stop_gradient = False
    base = jax.random.PRNGKey(42)
    with core_random.scoped_key(base):
        ref = np.asarray(seg(x).numpy())
        i_ref = core_random._scoped_stack[-1].i
    with core_random.scoped_key(base):
        got = np.asarray(rc.recompute(seg, x).numpy())
        i_got = core_random._scoped_stack[-1].i
    assert ref.tobytes() == got.tobytes()
    assert i_ref == i_got == 2


def test_zero_arg_forward_layer_recompute_runs_immediately():
    """A recompute-enabled Layer whose forward takes no inputs must
    still RUN (the public recompute()'s no-arg shape returns a wrapper;
    the Layer seam routes around it)."""
    class Gen(nn.Layer):
        def __init__(self):
            super().__init__()
            self.w = paddle.Parameter(np.ones((3, 3), np.float32))

        def forward(self):
            return (self.w * 2.0).sum()

    g = Gen()
    g.enable_recompute("full")
    out = g()
    assert float(np.asarray(out.numpy())) == 18.0
    out.backward()
    assert g.w._grad is not None


def test_eval_mode_skips_the_remat_region():
    m = _drop_mlp()
    m.enable_recompute("full")
    x = paddle.to_tensor(rng.rand(2, 16).astype("float32"))
    before = rc._seg_counter[0]
    m.eval()
    m(x)
    assert rc._seg_counter[0] == before  # no segment dispatched
    m.train()
    x2 = paddle.to_tensor(rng.rand(2, 16).astype("float32"))
    x2.stop_gradient = False
    m(x2)
    assert rc._seg_counter[0] > before
    m.disable_recompute()
    before = rc._seg_counter[0]
    m(x2)
    assert rc._seg_counter[0] == before


# -- the jaxpr-liveness meter (the bench claim's meter) ---------------------

def test_jaxpr_meter_shows_remat_savings():
    """Per-block full remat lowers the traced liveness peak of the
    compiled step — the deterministic CPU-side evidence the
    mlp_zero3_remat_jaxpr_peak_mb row gates (XLA CPU executables are
    remat-blind: barriers stripped + CSE)."""
    def build(remat):
        paddle.seed(0)
        blks = [nn.Sequential(nn.Linear(32, 256), nn.ReLU(),
                              nn.Linear(256, 32)) for _ in range(3)]
        m = nn.Sequential(*(blks + [nn.Linear(32, 8)]))
        opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                     learning_rate=0.01)
        if remat:
            for blk in blks:
                blk.enable_recompute("full")

        def one(x, y):
            loss = nn.functional.cross_entropy(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        step = paddle.jit.to_static(one, scan_steps=2)
        x = paddle.to_tensor(rng.rand(2, 512, 32).astype("float32"))
        y = paddle.to_tensor(rng.randint(0, 8, (2, 512)).astype("int64"))
        step(x, y)
        return next(iter(step.traced_memory_stats().values()))

    ctl = build(False)
    rem = build(True)
    assert rem["peak_bytes"] < ctl["peak_bytes"], (ctl, rem)
    assert ctl["argument_bytes"] == rem["argument_bytes"]


def test_jaxpr_meter_basics():
    import jax
    from paddle_tpu.observability import jaxpr_mem
    assert jaxpr_mem.aval_bytes(
        jax.ShapeDtypeStruct((4, 8), "float32")) == 128

    def f(a, b):
        c = a @ b       # born 128B
        d = c + 1.0     # c frees after this
        return d.sum()

    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((4, 8), "float32"),
                               jax.ShapeDtypeStruct((8, 4), "float32"))
    stats = jaxpr_mem.jaxpr_peak_stats(closed)
    assert stats["argument_bytes"] == 128 + 128
    assert stats["output_bytes"] == 4
    # high water at the matmul: both args live + c born (a/b free after
    # it, so d never coexists with them)
    assert stats["peak_bytes"] == 256 + 64


# -- XLA attribution: the host_offload kind ---------------------------------

def test_program_stats_carries_host_offload_kind():
    import jax
    from paddle_tpu.observability import memory
    compiled = jax.jit(lambda v: v * 2).lower(
        jax.ShapeDtypeStruct((8,), "float32")).compile()
    stats = memory.program_stats(compiled)
    assert stats["host_offload_bytes"] == 0  # CPU: nothing parked
    # records from pre-host_offload captures still export cleanly
    legacy = {f"{k}_bytes": 1 for k in memory.MEMORY_KINDS}
    legacy["peak_bytes"] = 1
    memory.export_program_memory("legacy_entry", legacy)


def test_state_ledger_has_host_offload_category():
    from paddle_tpu.observability import memory
    assert "host_offload" in memory.STATE_CATEGORIES
    # CPU arrays live in the device's DEFAULT host space: NOT parked
    t = paddle.to_tensor(np.ones((4,), np.float32))
    assert memory.is_host_parked(t._value) is False


# -- analysis integrations --------------------------------------------------

def test_remat_ladder_twin_verifies_clean():
    from paddle_tpu.analysis import errors, ladder
    findings, summary = ladder.verify_ladder(configs=["remat"])
    assert not findings, [str(f) for f in findings]
    assert summary["remat"] == [3, 9]  # fused surface vs expanded replay


def test_verifier_accepts_stamped_replay_rejects_unstamped():
    from paddle_tpu import static
    from paddle_tpu.analysis import check_graph, errors
    from paddle_tpu.static.program import _OpRecord

    def build(stamped):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            w = static.create_parameter([4, 4], "float32")
            h = paddle.matmul(x, w)
            loss = paddle.mean(h)
        op = prog.ops[0]
        replay = (lambda *a, _fn=op.fn, **k: _fn(*a, **k))
        if stamped:
            replay = rc.remat_replay(replay)
        prog.ops.append(_OpRecord(replay, op.arg_slots, op.kwarg_slots,
                                  op.out_slots, op.name))
        with static.program_guard(prog):
            g = paddle.sum(h)
        return prog, [loss, g]

    prog, targets = build(stamped=True)
    assert not errors(check_graph(prog, targets=targets))
    prog, targets = build(stamped=False)
    bad = errors(check_graph(prog, targets=targets))
    assert any(f.rule == "duplicate-slot-write" for f in bad)

    # a STAMPED op computing from DIFFERENT inputs into the slot is not
    # a rematerialization — the exemption is structural, not name-based
    prog, targets = build(stamped=True)
    replay_op = next(op for op in prog.ops if rc.is_remat_replay(op.fn))
    replay_op.arg_slots = list(reversed(replay_op.arg_slots))
    bad = errors(check_graph(prog, targets=targets))
    assert any(f.rule == "duplicate-slot-write" for f in bad)


def test_raw_remat_lint_rule(tmp_path):
    from paddle_tpu.analysis import lint_source
    p = tmp_path / "model.py"
    p.write_text(
        "import jax\n"
        "from jax import checkpoint as ckpt\n"
        "def forward(x):\n"
        "    return jax.checkpoint(lambda v: v * 2)(x)\n"
        "def forward2(x):\n"
        "    return jax.remat(lambda v: v + 1)(x)\n"
        "def forward3(x):\n"
        "    return ckpt(lambda v: v - 1)(x)\n"
        "@jax.checkpoint\n"
        "def forward4(x):\n"
        "    return x * 3\n")
    found = [f for f in lint_source(paths=[str(p)])
             if f.rule == "raw-remat-outside-policy"]
    assert len(found) == 4  # dotted + remat + bare-import + decorator
    # the default sweep stays clean: the policy surface is the one caller
    assert not [f for f in lint_source()
                if f.rule == "raw-remat-outside-policy"]
    # ... and stays exempt even when named EXPLICITLY
    import os as _os
    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    assert not [f for f in lint_source(
                    paths=[_os.path.join(repo, "paddle_tpu",
                                         "recompute.py")])
                if f.rule == "raw-remat-outside-policy"]


def test_recompute_records_one_fused_op_under_program_guard():
    from paddle_tpu import static
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 8], "float32")
        blk = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 8))
        h = rc.recompute(blk, x, policy="full")
        loss = paddle.mean(h)
    names = prog.op_names()
    assert names.count("recompute") == 1
    # capture probes must NOT leak into the program
    assert "matmul" not in names[:names.index("recompute")]
    assert not prog.verify(targets=[loss])


def test_mem_view_diff(tmp_path, capsys):
    import json
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import mem_view

    def snap(peak, cat_bytes):
        return {"programs": {"step#0:scan": {
                    **{f"{k}_bytes": 10 for k in
                       ("argument", "output", "temp", "alias",
                        "generated_code")},
                    "peak_bytes": peak}},
                "state": {"categories": {"param": {
                              "bytes": cat_bytes,
                              "global_bytes": cat_bytes * 8,
                              "count": 2}},
                          "total_bytes": cat_bytes,
                          "total_global_bytes": cat_bytes * 8}}

    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(snap(4 << 20, 1 << 20)))
    b.write_text(json.dumps(snap(3 << 20, 2 << 20)))
    rc_code = mem_view.main(["--diff", str(a), str(b)])
    out = capsys.readouterr().out
    assert rc_code == 0
    assert "d_peak_mb" in out and "-1.000" in out   # program peak fell
    assert "+1.000" in out                          # param bytes rose
    # a budget combined with --diff gates the AFTER side, never no-ops
    assert mem_view.main(["--diff", str(a), str(b), "--budget-mb",
                          "2"]) == 3
    capsys.readouterr()
    assert mem_view.main(["--diff", str(a), str(b), "--budget-mb",
                          "64"]) == 0
    capsys.readouterr()
    with pytest.raises(SystemExit):
        mem_view.main(["--diff", str(a), str(b), "--out",
                       str(tmp_path / "c.json")])
    capsys.readouterr()
