"""Meta-optimizer stack (reference: fleet/meta_optimizers/* + the
fleet_meta_optimizer_base.py program-inspection test pattern — here the
inspectable artifact is the resolved wrapper stack, plus behavioral checks
per strategy)."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed.fleet as fleet
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.fleet.meta_optimizers import (
    AMPOptimizer, ASPOptimizer, DGCMomentumOptimizer, FP16AllReduceOptimizer,
    StrategyCompiler, apply_recompute)


def _model():
    paddle.seed(3)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _data():
    r = np.random.RandomState(0)
    return (paddle.to_tensor(r.rand(4, 8).astype("float32")),
            paddle.to_tensor(r.rand(4, 4).astype("float32")))


class TestStrategyCompiler:
    """The inspection tests: strategy flags → resolved stack names."""

    def _resolve(self, strategy, opt):
        return [n for n, _ in StrategyCompiler().resolve(strategy, None, opt)]

    def test_each_flag_resolves(self):
        m = _model()
        strategy = fleet.DistributedStrategy()
        strategy.gradient_merge = True
        strategy.fp16_allreduce = True
        strategy.amp = True
        strategy.asp = True
        opt = paddle.optimizer.Momentum(parameters=m.parameters())
        names = self._resolve(strategy, opt)
        assert names == ["fp16_allreduce", "gradient_merge", "asp", "amp"]

    def test_dgc_requires_momentum(self):
        m = _model()
        strategy = fleet.DistributedStrategy()
        strategy.dgc = True
        opt = paddle.optimizer.Momentum(parameters=m.parameters())
        assert self._resolve(strategy, opt) == ["dgc"]
        adam = paddle.optimizer.Adam(parameters=m.parameters())
        with pytest.warns(UserWarning, match="Momentum"):
            assert self._resolve(strategy, adam) == []

    def test_dgc_localsgd_conflict(self):
        m = _model()
        strategy = fleet.DistributedStrategy()
        strategy.dgc = True
        strategy.localsgd = True
        opt = paddle.optimizer.Momentum(parameters=m.parameters())
        with pytest.warns(UserWarning, match="conflicts"):
            names = self._resolve(strategy, opt)
        assert "dgc" in names and "localsgd" not in names

    def test_lamb_replaces_adam(self):
        from paddle_tpu.optimizer import Lamb
        m = _model()
        strategy = fleet.DistributedStrategy()
        strategy.lamb = True
        opt = paddle.optimizer.Adam(parameters=m.parameters())
        stack = StrategyCompiler().resolve(strategy, None, opt)
        assert [n for n, _ in stack] == ["lamb"]
        rebuilt = StrategyCompiler.apply(stack, opt)
        assert isinstance(rebuilt, Lamb)

    def test_distributed_optimizer_records_stack(self):
        fleet.init()
        strategy = fleet.DistributedStrategy()
        strategy.gradient_merge = True
        m = _model()
        opt = fleet.distributed_optimizer(
            paddle.optimizer.SGD(parameters=m.parameters()), strategy)
        assert opt._meta_optimizer_names == ["gradient_merge"]


class TestDGC:
    def test_rampup_matches_momentum(self):
        x, y = _data()
        paddle.seed(3)
        m1 = _model()
        dgc = DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9,
                                   rampup_begin_step=100,
                                   parameters=m1.parameters())
        paddle.seed(3)
        m2 = _model()
        mom = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                        parameters=m2.parameters())
        for _ in range(3):
            for mod, opt in ((m1, dgc), (m2, mom)):
                loss = nn.functional.mse_loss(mod(x), y)
                loss.backward()
                opt.step()
                opt.clear_grad()
        np.testing.assert_allclose(m1[0].weight.numpy(), m2[0].weight.numpy(),
                                   rtol=1e-5)

    def test_topk_sparsifies_with_error_feedback(self):
        x, y = _data()
        m = _model()
        dgc = DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9,
                                   rampup_begin_step=0, sparsity=[0.75],
                                   parameters=m.parameters())
        w_before = m[0].weight.numpy().copy()
        loss = nn.functional.mse_loss(m(x), y)
        loss.backward()
        dgc.step()
        delta = m[0].weight.numpy() - w_before
        nz = (np.abs(delta) > 0).mean()
        # ~25% of entries updated (top-25% by |v|)
        assert 0.05 < nz < 0.5
        # the skipped mass lives in the error-feedback accumulator
        v = dgc._get_accumulator("dgc_v", m[0].weight)
        assert float(jnp.abs(v._value).sum()) > 0

    def test_error_feedback_converges(self):
        """With error feedback, sparse updates still drive the loss down."""
        x, y = _data()
        m = _model()
        dgc = DGCMomentumOptimizer(learning_rate=0.05, momentum=0.9,
                                   rampup_begin_step=0, sparsity=[0.9],
                                   parameters=m.parameters())
        losses = []
        for _ in range(30):
            loss = nn.functional.mse_loss(m(x), y)
            losses.append(float(loss.numpy()))
            loss.backward()
            dgc.step()
            dgc.clear_grad()
        assert losses[-1] < losses[0] * 0.5


class TestFP16AllReduce:
    def test_grads_quantized_through_fp16(self):
        m = _model()
        opt = FP16AllReduceOptimizer(
            paddle.optimizer.SGD(learning_rate=0.0,
                                 parameters=m.parameters()))
        x, y = _data()
        loss = nn.functional.mse_loss(m(x), y)
        loss.backward()
        g32 = m[0].weight._grad
        opt._quantize_grads()
        g16 = m[0].weight._grad
        assert g16.dtype == jnp.float32  # cast back after the wire
        np.testing.assert_allclose(np.asarray(g16),
                                   np.asarray(g32).astype(np.float16),
                                   rtol=1e-3)


class TestAMPMetaOptimizer:
    def test_scaled_training_step(self):
        m = _model()
        amp = AMPOptimizer(
            paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=m.parameters()),
            {"init_loss_scaling": 1024.0})
        x, y = _data()
        w0 = m[0].weight.numpy().copy()
        loss = nn.functional.mse_loss(m(x), y)
        amp.minimize(loss)
        assert not np.allclose(m[0].weight.numpy(), w0)
        # reference parity: the applied update is the UNscaled gradient
        paddle.seed(3)
        ref = _model()
        sgd = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=ref.parameters())
        loss = nn.functional.mse_loss(ref(x), y)
        loss.backward()
        sgd.step()
        np.testing.assert_allclose(m[0].weight.numpy(), ref[0].weight.numpy(),
                                   rtol=1e-4, atol=1e-6)


class TestASPMetaOptimizer:
    def test_masks_survive_steps(self):
        from paddle_tpu.sparsity import prune_model, check_mask_1d
        m = _model()
        prune_model(m)
        opt = ASPOptimizer(paddle.optimizer.SGD(learning_rate=0.1,
                                                parameters=m.parameters()))
        x, y = _data()
        for _ in range(3):
            loss = nn.functional.mse_loss(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert check_mask_1d(m[0].weight.numpy(), 2, 4)


class TestRecompute:
    def test_apply_recompute_wraps_and_trains(self):
        m = _model()
        wrapped = apply_recompute(m, ["0", "2"])  # both Linears
        assert len(wrapped) == 2
        x, y = _data()
        loss = nn.functional.mse_loss(m(x), y)
        loss.backward()
        assert m[0].weight._grad is not None
        # parity with un-wrapped model
        paddle.seed(3)
        ref = _model()
        loss_ref = nn.functional.mse_loss(ref(x), y)
        loss_ref.backward()
        np.testing.assert_allclose(np.asarray(m[0].weight._grad),
                                   np.asarray(ref[0].weight._grad),
                                   rtol=1e-5)
