"""HBM memory accounting (ISSUE 10): per-program XLA attribution,
framework-state residency ledger, OOM-classified flight dumps, run-log
rotation, the label-cardinality guard, and the lower-is-better memory
gate.

The headline contract: ``StaticFunction.memory_stats()`` returns
argument/output/temp/alias/generated-code bytes for every compiled
entry, and the ZeRO-3 ledger proves model-state residency ≈ 1/dp of the
replicated control NUMERICALLY on the 8-device CPU mesh — byte
accounting is backend-deterministic, so these are value assertions, not
pattern matches.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn
from paddle_tpu.distributed import parallel_env
from paddle_tpu.observability import export as obs_export
from paddle_tpu.observability import memory

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DP = 8

rng = np.random.RandomState(11)


def _build(zero_stage, k, accumulate=None, feat=64, hidden=128,
           classes=32, seed=5):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(feat, hidden), nn.ReLU(),
                      nn.Linear(hidden, classes))
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 learning_rate=0.05)
    if zero_stage:
        opt._zero_enable(axis="dp", stage=zero_stage)

    def one(xb, yb):
        loss = nn.functional.cross_entropy(m(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(one, scan_steps=k,
                                dp_axis="dp" if zero_stage else None,
                                accumulate_steps=accumulate)
    return step, m, opt


def _batches(k, batch=16, feat=64, classes=32):
    x = rng.rand(k, batch, feat).astype("float32")
    y = rng.randint(0, classes, (k, batch)).astype("int64")
    return paddle.to_tensor(x), paddle.to_tensor(y)


@pytest.fixture
def _mesh():
    mesh = parallel_env.make_mesh({"dp": DP})
    parallel_env.set_mesh(mesh)
    yield mesh
    parallel_env.set_mesh(None)


# -- per-program attribution ----------------------------------------------

@pytest.mark.parametrize("k", [1, 4])
@pytest.mark.parametrize("zero,acc", [(0, None), (1, None), (3, None),
                                      (3, 2)],
                         ids=["zero0", "zero1", "zero3", "zero3_acc2"])
def test_memory_stats_sharding_matrix(_mesh, k, zero, acc):
    """Every compiled entry across the sharding matrix yields the full
    byte breakdown, and the donated carry shows up as aliased (not
    double-billed) bytes."""
    if acc is not None and k % acc:
        pytest.skip("k must be a multiple of accumulate_steps")
    step, _m, _opt = _build(zero, k, accumulate=acc)
    x, y = _batches(k)
    step(x, y)
    stats = step.memory_stats()
    assert len(stats) == 1
    (label, rec), = stats.items()
    assert ":scan" in label
    for kind in memory.MEMORY_KINDS:
        assert rec[f"{kind}_bytes"] >= 0, kind
    assert rec["peak_bytes"] == memory.peak_bytes(rec)
    # the framework state rides the carry donated: XLA reports the
    # aliased input/output pairs, so peak counts the state once
    assert rec["alias_bytes"] > 0
    assert rec["argument_bytes"] > rec["alias_bytes"]


def test_temp_bytes_scale_with_microbatch_not_k(_mesh):
    """Scan temps are per-step workspace reused across iterations: 4x
    the scan length leaves temp bytes ~flat (xs arguments grow
    instead), while 4x the microbatch grows temps ~linearly — the
    decomposition that makes batch/k tuning a calculation instead of an
    OOM hunt."""
    def temp_of(k, batch):
        step, _m, _opt = _build(0, k)
        x, y = _batches(k, batch=batch)
        step(x, y)
        (rec,) = step.memory_stats().values()
        return rec["temp_bytes"], rec["argument_bytes"]

    t_k1, a_k1 = temp_of(1, 16)
    t_k4, a_k4 = temp_of(4, 16)
    t_b64, _ = temp_of(1, 64)
    assert t_k4 < t_k1 * 2, (t_k1, t_k4)       # temps ~O(1) in k
    # argument growth is exactly the extra xs steps (the carried state
    # is k-invariant): 3 more [16, 64] float32 batches + labels
    xs_step = 16 * 64 * 4
    assert 2 * xs_step <= a_k4 - a_k1 <= 5 * xs_step, (a_k1, a_k4)
    # 4x the microbatch at least doubles temps (activations scale;
    # the param-sized constant workspace dilutes the slope below 4x)
    assert t_b64 >= t_k1 * 2.0, (t_k1, t_b64)


def test_zero3_state_resident_1_over_dp_numerically(_mesh):
    """THE acceptance number: ZeRO-3 model-state residency per rank ==
    rows/dp of the flat layout, and ≈ 1/dp of the analytically-known
    replicated model state (params + both Adam moments) within the
    row-padding slack — the claim the dryrun HLO rows only
    pattern-match, closed with bytes."""
    k = 2
    feat, hidden, classes = 256, 512, 64
    step, m, opt = _build(3, k, feat=feat, hidden=hidden, classes=classes)
    x, y = _batches(k, feat=feat, classes=classes)
    step(x, y)

    # expected per-rank bytes, straight from the flat layout (gacc is a
    # window accumulator with no replicated-control counterpart — the
    # model-state comparison covers param + moment1 + moment2)
    expected = 0
    for zb, sdict in zip(opt._zero["buckets"], opt._zero["stores"]):
        for slot, store in sdict.items():
            if slot == "gacc":
                continue
            itemsize = np.dtype(store.tensor._value.dtype).itemsize
            expected += (zb.rows // zb.degree) * 1024 * itemsize

    measured = 0
    for sdict in opt._zero["stores"]:
        for slot, store in sdict.items():
            if slot == "gacc":
                continue
            _g, r = memory.value_bytes(store.tensor._value)
            measured += r
    assert measured == expected, (measured, expected)

    # vs the replicated control: params + moment1 + moment2, all fp32
    n_elems = sum(int(np.prod(p._value.shape)) for p in m.parameters())
    replicated = 3 * n_elems * 4
    ratio = measured * DP / replicated
    # padding (per-param row alignment + shard-degree pad rows) only
    # ever adds bytes; at this model size the slack is under 10%
    assert 1.0 <= ratio < 1.10, (measured, replicated, ratio)

    # and the ledger's category walk agrees with the direct store walk
    led = memory.state_ledger()
    cat_bytes = sum(led["categories"].get(c, {"bytes": 0})["bytes"]
                    for c in ("zero_param", "zero_moment", "zero_master",
                              "gacc"))
    assert cat_bytes >= measured  # >= : other live tests' stores may add


def test_memory_stats_before_run_raises(_mesh):
    step, _m, _opt = _build(0, 2)
    with pytest.raises(RuntimeError, match="call the step once"):
        step.memory_stats()


def test_export_memory_stats_gauges_and_registry(_mesh):
    step, _m, _opt = _build(0, 2)
    x, y = _batches(2)
    step(x, y)
    step.export_memory_stats()
    gauges = obs_export.gauges()
    keys = [g for g in gauges if g.startswith("program_hbm_bytes{")
            and "one#0:scan" in g]
    kinds = {g.split('kind="')[1].rstrip('"}') for g in keys}
    assert set(memory.MEMORY_KINDS) | {"peak"} <= kinds
    reg = memory.program_memory()
    (entry,) = [e for e in reg if e.startswith("one#0")]
    assert reg[entry]["top_buffers"], "top buffers must ride the registry"
    text = obs_export.prometheus_text()
    assert "program_hbm_bytes{" in text


# -- state ledger ----------------------------------------------------------

def test_state_ledger_categories_and_bytes():
    paddle.seed(0)
    m = nn.Linear(32, 16)
    opt = paddle.optimizer.Adam(parameters=m.parameters(),
                                learning_rate=0.01)
    led = memory.export_state_ledger()
    cats = led["categories"]
    for cat in ("param", "opt_moment", "lr", "rng"):
        assert cat in cats, cats.keys()
    # this model's params: (32*16 + 16) * 4 bytes, replicated resident
    mine = [e for e in led["entries"]
            if e["category"] == "param"
            and e["name"] in {p.name for p in m.parameters()}]
    assert sum(e["bytes"] for e in mine) == (32 * 16 + 16) * 4
    for e in mine:
        assert e["bytes"] == e["global_bytes"]  # replicated
    assert led["total_bytes"] >= sum(e["bytes"] for e in mine)
    gauges = obs_export.gauges()
    assert 'state_resident_bytes{category="param"}' in gauges
    assert "state_resident_bytes_total" in gauges
    del opt  # keep the optimizer alive through the walk above


def test_is_oom_error():
    assert memory.is_oom_error(MemoryError())
    assert memory.is_oom_error(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                     "17179869184 bytes"))
    assert memory.is_oom_error(ValueError("failed to allocate request"))
    assert not memory.is_oom_error(RuntimeError("shape mismatch"))
    assert not memory.is_oom_error(None)


def test_attribute_program_unrecorded_target_raises():
    from paddle_tpu import static
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 4], "float32")
        y = paddle.mean(x)
    ghost = paddle.to_tensor(np.zeros((1,), np.float32))
    with pytest.raises(memory.MemoryAttributionError):
        memory.attribute_program(prog, [ghost])
    stats = memory.attribute_program(prog, [y])
    assert stats["peak_bytes"] > 0


# -- gate: lower-is-better memory rows ------------------------------------

def test_gate_direction_lower_for_memory_rows():
    from paddle_tpu.observability import gate
    base = {"m_hbm_peak_mb": {"metric": "m_hbm_peak_mb", "value": 100.0,
                              "unit": "MB", "direction": "lower",
                              "backend": "cpu"}}
    grown = {"m_hbm_peak_mb": {"metric": "m_hbm_peak_mb", "value": 130.0,
                               "unit": "MB", "backend": "cpu"}}
    ok, report = gate.compare(base, grown)
    assert not ok and report[0]["status"] == "REGRESSION"
    shrunk = {"m_hbm_peak_mb": {"metric": "m_hbm_peak_mb", "value": 80.0,
                                "unit": "MB", "backend": "cpu"}}
    ok, report = gate.compare(base, shrunk)
    assert ok and report[0]["status"] == "IMPROVED"
    # bare "MB" unit (no direction pin) also defaults lower-is-better;
    # rates like MB/s stay higher-is-better
    assert not gate.higher_is_better({"unit": "MB"})
    assert gate.higher_is_better({"unit": "MB/s"})
    assert gate.higher_is_better({"unit": "MB", "direction": "higher"})


def test_perf_gate_exits_2_on_inflated_hbm_row(tmp_path):
    """Acceptance: tools/perf_gate.py exit code 2 when a *_hbm_peak_mb
    row regresses past tolerance vs BASELINE_PERF.json (synthetic
    inflated record), and 0 when it matches."""
    with open(os.path.join(REPO, "BASELINE_PERF.json")) as f:
        rows = json.load(f)["results"]
    hbm = [r for r in rows if r["metric"].endswith("_hbm_peak_mb")]
    assert hbm, "BASELINE_PERF.json must pin an *_hbm_peak_mb row"
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"results": hbm}))

    def run(value):
        cur = dict(hbm[0])
        cur["value"] = value
        cur_p = tmp_path / "cur.json"
        cur_p.write_text(json.dumps({"results": [cur]}))
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
             "--baseline", str(base), "--current", str(cur_p)],
            capture_output=True, text=True, cwd=REPO, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        return r.returncode, r.stdout

    rc, out = run(hbm[0]["value"] * 2)  # inflated: memory regression
    assert rc == 2 and "REGRESSION" in out, out
    rc, out = run(hbm[0]["value"])
    assert rc == 0 and "PASS" in out, out


# -- label-cardinality guard ----------------------------------------------

def test_label_cardinality_guard(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_MAX_LABEL_SETS", "3")
    obs_export.clear_label_sets()
    before = monitor.stat_get("metrics_label_overflow_total")
    admitted = [obs_export.format_labels("guard_test_metric", op=f"op{i}")
                for i in range(3)]
    assert all(f'op="op{i}"' in s for i, s in enumerate(admitted))
    # 4th distinct combination collapses; the admitted ones keep working
    over = obs_export.format_labels("guard_test_metric", op="op3")
    assert over == '{op="__overflow__"}'
    assert monitor.stat_get("metrics_label_overflow_total") == before + 1
    again = obs_export.format_labels("guard_test_metric", op="op1")
    assert again == admitted[1]
    # other metrics are unaffected (per-metric bound)
    other = obs_export.format_labels("guard_other_metric", op="op9")
    assert 'op="op9"' in other
    # metric-less calls (legacy producers) bypass the guard entirely
    free = obs_export.format_labels(op="op77")
    assert 'op="op77"' in free
    obs_export.clear_label_sets()


# -- run-log rotation ------------------------------------------------------

def test_runlog_rotation_parts_and_merge(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_view
    from paddle_tpu.observability import runlog

    log = runlog.start_run(dir=str(tmp_path), run_id="rot", rank=0,
                           max_bytes=4096)
    n_events = 300
    for i in range(n_events):
        runlog.event("tick", i=i, pad="x" * 64)
    runlog.stop_run()

    assert log.part >= 2, "300 padded events must roll a 4KB log"
    assert len(log.paths) == log.part + 1
    for p in log.paths:
        assert os.path.exists(p)
        assert os.path.getsize(p) < 4096 + 4096  # bounded per part
    # continuation manifests chain the parts
    with open(log.paths[1]) as f:
        first = json.loads(f.readline())
    assert first["kind"] == "manifest" and first["part"] == 1
    assert first["continues"] == os.path.basename(log.paths[0])

    # trace_view merges parts transparently: one process track, no
    # event lost
    events, n_bad = trace_view.load_events(log.paths)
    assert n_bad == 0
    ticks = [e for e in events if e.get("event") == "tick"]
    assert len(ticks) == n_events
    assert {e["i"] for e in ticks} == set(range(n_events))
    assert {e["_file"] for e in events} == {log.base_path}
    trace = trace_view.build_chrome_trace(events)
    tracks = [e for e in trace["traceEvents"]
              if e.get("name") == "process_name"]
    assert len(tracks) == 1


def test_runlog_env_max_mb(tmp_path, monkeypatch):
    from paddle_tpu.observability import runlog
    monkeypatch.setenv("PADDLE_TPU_RUNLOG_MAX_MB", "0.01")  # ~10 KB
    log = runlog.start_run(dir=str(tmp_path), run_id="envrot", rank=0)
    assert log.max_bytes == int(0.01 * 1024 * 1024)
    runlog.stop_run()


def test_steptimer_window_boundary_memory_snapshot(tmp_path):
    from paddle_tpu.observability import StepTimer, runlog
    runlog.start_run(dir=str(tmp_path), run_id="memsnap", rank=0)
    t = StepTimer(window=2, tokens_per_step=10, publish_as="memtest")
    for _ in range(5):
        t.step()
    log_path = runlog.log_path()
    runlog.stop_run()
    with open(log_path) as f:
        recs = [json.loads(line) for line in f]
    snaps = [r for r in recs if r.get("event") == "memory_snapshot"]
    # boundaries at total_steps 2 and 4 (first step only anchors)
    assert len(snaps) == 2
    for s in snaps:
        assert "state" in s and "categories" in s["state"]
        assert s["state"]["total_bytes"] >= 0


# -- mem_view --------------------------------------------------------------

def test_mem_view_snapshot_and_budget(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import mem_view

    memory.record_program_memory("mv_test", {
        "argument_bytes": 4 << 20, "output_bytes": 1 << 20,
        "temp_bytes": 8 << 20, "alias_bytes": 2 << 20,
        "generated_code_bytes": 0, "peak_bytes": 11 << 20})
    snap = tmp_path / "snap.json"
    snap.write_text(json.dumps(memory.snapshot()))

    rc = mem_view.main(["--snapshot", str(snap), "--budget-mb", "64"])
    assert rc == 0
    rc = mem_view.main(["--snapshot", str(snap), "--budget-mb", "1"])
    assert rc == 3

    table = mem_view.format_program_table(
        {"mv_test": memory.program_memory()["mv_test"]})
    assert "mv_test" in table and "11.000" in table
    ok, over = mem_view.check_budget(
        {"bad": {"error": "boom"}}, budget_mb=1e9)
    assert not ok and over == [("bad", None)]
    memory.clear_program_memory()


def test_mem_view_flight_dump_source(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import mem_view
    dump = {"reason": "oom", "memory": {
        "programs": {"p": {"argument_bytes": 0, "output_bytes": 0,
                           "temp_bytes": 0, "alias_bytes": 0,
                           "generated_code_bytes": 0,
                           "peak_bytes": 2 << 20}},
        "state": {"categories": {"param": {"bytes": 10, "global_bytes":
                                           10, "count": 1}},
                  "total_bytes": 10, "total_global_bytes": 10}}}
    p = tmp_path / "flight.json"
    p.write_text(json.dumps(dump))
    assert mem_view.main(["--snapshot", str(p)]) == 0
    assert mem_view.main(["--snapshot", str(p), "--budget-mb", "1"]) == 3


# -- serving engine --------------------------------------------------------

def test_serving_engine_per_bucket_memory():
    import paddle_tpu.serving as serving
    from paddle_tpu.jit.to_static import InputSpec

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model.eval()
    engine = serving.Engine.from_layer(
        model, [InputSpec([None, 8], "float32")], bucket_ladder=(1, 4))
    try:
        stats = engine.memory_stats()
    finally:
        engine.close()
    assert set(stats) == {1, 4}
    for b, rec in stats.items():
        assert rec["peak_bytes"] > 0
        assert rec["argument_bytes"] > 0
    # bigger bucket, bigger activations
    assert stats[4]["peak_bytes"] > stats[1]["peak_bytes"]
    reg = memory.program_memory()
    assert "serving_b1" in reg and "serving_b4" in reg
    memory.clear_program_memory()


# -- OOM-classified flight dump (chaos) ------------------------------------

@pytest.mark.chaos
def test_oom_classified_flight_dump(tmp_path, _mesh):
    """Acceptance: a RESOURCE_EXHAUSTED death produces a dump tagged
    reason="oom" whose memory section carries per-category state bytes
    and the top-N buffers of the recorded programs."""
    from paddle_tpu.observability import flight
    from paddle_tpu.testing import faults

    step, _m, _opt = _build(3, 2)
    x, y = _batches(2)
    step(x, y)
    step.export_memory_stats()  # program + top buffers in the registry

    flight.install(str(tmp_path))
    try:
        faults.inject("jit/step", exc=RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 17179869184 "
            "bytes (XLA allocator ran out of HBM)"))
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            step(x, y)
    finally:
        faults.reset()
        flight.uninstall()

    path = flight.latest_dump(str(tmp_path))
    assert path is not None
    with open(path) as f:
        dump = json.load(f)
    assert dump["reason"] == "oom"
    assert dump["cause"] == "kill_point"
    assert dump["kill_point"] == "jit/step"
    assert "RESOURCE_EXHAUSTED" in dump["exception"]["message"]
    mem = dump["memory"]
    cats = mem["state"]["categories"]
    assert {"zero_param", "zero_moment"} <= set(cats)
    assert all(c["bytes"] > 0 for k, c in cats.items()
               if k.startswith("zero_"))
    progs = [p for p in mem["programs"] if p.startswith("one#0")]
    assert progs, mem["programs"].keys()
    bufs = mem["programs"][progs[0]]["top_buffers"]
    assert bufs and bufs[0]["bytes"] >= bufs[-1]["bytes"]
    memory.clear_program_memory()


@pytest.mark.chaos
def test_non_oom_kill_point_dump_stays_kill_point(tmp_path):
    from paddle_tpu.observability import flight
    from paddle_tpu.testing import faults

    flight.install(str(tmp_path))
    try:
        faults.inject("jit/step", exc=RuntimeError("plain failure"))
        step, _m, _opt = _build(0, 1)
        # build on the fresh default mesh-less path
        x, y = _batches(1)
        with pytest.raises(RuntimeError, match="plain failure"):
            step(x, y)
    finally:
        faults.reset()
        flight.uninstall()
    with open(flight.latest_dump(str(tmp_path))) as f:
        dump = json.load(f)
    assert dump["reason"] == "kill_point"
    assert "memory" in dump  # every dump carries the section
