"""paddle_tpu.analysis — program verifier, dtype checker, donation/collective
hazard detection, lint, and the debug-mode pass hooks.

The five seeded defect classes the verifier must catch (ISSUE 3 acceptance):
use-before-def, dtype drift, donated-slot reuse, collective-order mismatch,
dangling buffer update.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.analysis as analysis
from paddle_tpu import nn, static
from paddle_tpu.core.dispatch import call_op
from paddle_tpu.static.passes import _shallow_clone
from paddle_tpu.static.program import _OpRecord, _Slot

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _simple_prog():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 4], "float32")
        w = static.create_parameter([4, 3], "float32")
        h = paddle.matmul(x, w)
        y = paddle.tanh(h)
        loss = paddle.mean(y)
    return prog, x, w, y, loss


def _bn_prog():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 4, 3, 3], "float32")
        bn = nn.BatchNorm2D(4)
        y = bn(x)
        loss = paddle.mean(y)
    return prog, bn, loss


def _rules(findings):
    return {f.rule for f in findings}


class TestGraphVerifier:
    def test_clean_program_no_findings(self):
        prog, *_, loss = _simple_prog()
        assert analysis.verify(prog, targets=[loss]) == []

    def test_use_before_def(self):
        """Seeded defect 1: a broken rewrite drops a producer."""
        prog, *_ = _simple_prog()
        bad = _shallow_clone(prog, prog.ops[1:])  # tanh now reads a ghost
        fs = analysis.verify(bad)
        assert "use-before-def" in _rules(fs)
        assert any(f.severity == "error" for f in fs)
        with pytest.raises(analysis.VerifyError, match="use-before-def"):
            analysis.verify(bad, raise_on_error=True)

    def test_duplicate_slot_write(self):
        prog, *_ = _simple_prog()
        dup = prog.ops[1]
        bad = _shallow_clone(prog, list(prog.ops) + [
            _OpRecord(dup.fn, dup.arg_slots, dup.kwarg_slots,
                      dup.out_slots, dup.name)])
        fs = analysis.check_graph(bad)
        assert any(f.rule == "duplicate-slot-write" and
                   f.severity == "error" for f in fs)

    def test_dangling_buffer_update(self):
        """Seeded defect 5: stat-update producer dropped but the buffer
        alias kept (what a forgetful pass does)."""
        prog, _bn, _loss = _bn_prog()
        assert prog._buffer_updates  # the BN program records the aliases
        bad = _shallow_clone(prog, [op for op in prog.ops
                                    if op.name != "batch_norm_stat_update"])
        fs = analysis.verify(bad)
        assert "dangling-buffer-update" in _rules(fs)
        # the real pass (and prune) filter the aliases: clean
        good = static.apply_pass(prog, "remove_stat_update_pass")
        assert "dangling-buffer-update" not in _rules(analysis.verify(good))

    def test_dead_op_needs_targets(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            a = paddle.tanh(x)
            b = paddle.mean(a)
            c = paddle.exp(x)      # dead for fetch=b
            _d = paddle.sum(c)
        fs = analysis.verify(prog, targets=[b])
        dead = [f for f in fs if f.rule == "dead-op"]
        assert {f.op_name for f in dead} == {"exp", "sum"}
        # without a fetch set dead-ness is undecidable: no dead findings
        assert "dead-op" not in _rules(analysis.verify(prog))

    def test_unused_inputs_flagged(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            _unused = static.data("y", [2], "int64")
            w = static.create_parameter([4, 3], "float32")
            paddle.matmul(x, w)
        # a param slot no kept op references — the pre-fix prune() left
        # every original input in the signature like this
        w2 = static.create_parameter([3, 3], "float32")
        prog._slot_of(w2)
        rules = _rules(analysis.check_graph(prog))
        assert "unused-feed" in rules
        assert "unused-program-input" in rules


class TestDtypeChecker:
    def test_amp_boundary_drift(self):
        """Seeded defect 2 (dtype drift): a layer_norm-class op eats bf16
        but returns fp32 — the missing AMP output downcast."""
        import jax.numpy as jnp
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "bfloat16")
            call_op(lambda v: jnp.asarray(v, jnp.float32),
                    x, op_name="layer_norm")
        fs = analysis.check_dtypes(prog)
        assert any(f.rule == "amp-boundary-upcast" and
                   f.op_name == "layer_norm" for f in fs)

    def test_mixed_precision_matmul(self):
        import jax.numpy as jnp
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "bfloat16")
            w = static.create_parameter([4, 3], "float32")  # master leak
            call_op(lambda a, b: jnp.matmul(a, b.astype(a.dtype)),
                    x, w, op_name="matmul")
        fs = analysis.check_dtypes(prog)
        assert any(f.rule == "mixed-precision-input" for f in fs)

    def test_shape_specialization(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [-1, 4], "float32")
            y = paddle.reshape(x, [1, 4])  # bakes the dynamic batch
            paddle.mean(y)
        fs = analysis.check_dtypes(prog)
        assert any(f.rule == "shape-specialization" and
                   f.severity == "error" for f in fs)

    def test_polymorphic_program_clean(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [-1, 4], "float32")
            paddle.mean(paddle.tanh(x))
        assert analysis.check_dtypes(prog) == []


class TestDonation:
    def test_donated_buffer_alias_read(self):
        """Seeded defect 3 (donated-slot reuse): when the BN buffers ride a
        donated carry, the normalize op's read AFTER the stat update is a
        stale-buffer read."""
        prog, _bn, _loss = _bn_prog()
        donated = set(prog._buffer_updates)
        fs = analysis.check_donation(prog, donated=donated)
        assert fs and all(f.rule == "donated-slot-reuse" for f in fs)
        # without donation the write-back is deferred: the same read is
        # legal (the executor assigns buffers after the run)
        assert analysis.check_donation(prog, donated=set()) == []

    def test_donated_input_overwrite(self):
        prog, x, w, *_ = _simple_prog()
        w_slot = prog._slot_of(w, create=False)
        x_slot = prog._slot_of(x, create=False)
        bad = _shallow_clone(prog, list(prog.ops) + [
            _OpRecord(lambda v: v, [_Slot(x_slot)], {}, [w_slot], "assign")])
        fs = analysis.check_donation(bad, donated={w_slot})
        assert any(f.rule == "donated-slot-reuse" for f in fs)
        # the graph verifier independently warns on the input overwrite
        assert any(f.rule == "input-overwrite"
                   for f in analysis.check_graph(bad))

    def test_static_function_partition(self):
        lin = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(parameters=lin.parameters(),
                                   learning_rate=0.1)

        def step(xb):
            loss = lin(xb).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        sfn = paddle.jit.to_static(step)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        sfn(x)
        assert analysis.errors(sfn.verify()) == []
        # seeded hazard: a donated uid also threaded read-only
        donated = sfn._last_partition["donated"]
        assert donated
        sfn._last_partition["readonly"] = list(
            sfn._last_partition["readonly"]) + [donated[0]]
        bad = sfn.verify()
        assert any(f.rule == "donated-slot-reuse" and f.severity == "error"
                   for f in bad)


class TestCollectives:
    @staticmethod
    def _rank_prog(seq):
        prog = static.Program()
        with static.program_guard(prog):
            g = static.data("grad", [4], "float32")
            out = g
            for name, ax in seq:
                def _c(v):
                    return v
                _c._collective_axis = ax
                out = call_op(_c, out, op_name=name)
            paddle.sum(out)
        return prog

    def test_order_mismatch(self):
        """Seeded defect 4: ranks disagree on the collective schedule."""
        p0 = self._rank_prog([("c_allreduce", "dp"), ("c_broadcast", "dp")])
        # the SAME collectives in a different order is the precise
        # schedule-skew diagnosis (one rank pipelined, the other not) —
        # still an error: the wire cross-matches and deadlocks
        p1 = self._rank_prog([("c_broadcast", "dp"), ("c_allreduce", "dp")])
        fs = analysis.check_collective_order([p0, p1], mesh_axes=("dp",))
        assert any(f.rule == "collective-schedule-skew" and
                   f.severity == "error" for f in fs)
        # axis skew at the same position is a genuine divergence (the
        # multisets differ) — NOT collapsed into schedule skew
        p2 = self._rank_prog([("c_allreduce", "mp"), ("c_broadcast", "dp")])
        fs = analysis.check_collective_order([p0, p2],
                                             mesh_axes=("dp", "mp"))
        assert any(f.rule == "collective-order-mismatch" for f in fs)
        assert not any(f.rule == "collective-schedule-skew" for f in fs)
        # length skew deadlocks too
        p3 = self._rank_prog([("c_allreduce", "dp")])
        fs = analysis.check_collective_order([p0, p3], mesh_axes=("dp",))
        assert any("deadlock" in f.message for f in fs)

    def test_matching_ranks_clean(self):
        seq = [("c_allreduce", "dp"), ("c_broadcast", "dp")]
        progs = [self._rank_prog(seq), self._rank_prog(seq)]
        assert analysis.check_collective_order(progs,
                                               mesh_axes=("dp",)) == []

    def test_pipelined_twin_order_and_skew(self):
        """The prefetch-pipelined zero3 twin: identical pipelined ranks
        verify clean; a serial rank mixed with a pipelined rank is
        flagged (different collective count — the prefetch twin carries
        the tail re-gather); and the twin's recorded sequence scores
        every stamped payload as schedulable, strictly above the serial
        twin's."""
        from paddle_tpu.analysis import ladder
        from paddle_tpu.analysis.collectives import sequence_overlap_score
        piped = [p for p, _t in ladder._zero3_prefetch_ranks()]
        assert analysis.check_collective_order(
            piped, mesh_axes=("dp",)) == []
        serial = [p for p, _t in ladder._zero3_ranks()]
        fs = analysis.check_collective_order([serial[0], piped[0]],
                                             mesh_axes=("dp",))
        assert any(f.severity == "error" for f in fs)
        s_piped = sequence_overlap_score(piped[0])
        s_serial = sequence_overlap_score(serial[0])
        assert s_piped["schedulable_overlap"] == 1.0
        assert (s_serial["schedulable_overlap"]
                < s_piped["schedulable_overlap"])
        # every pipelined collective names its emission-order slack
        assert all(rec["schedulable"]
                   for rec in s_piped["per_collective"])

    def test_schedule_skew_same_count(self):
        """Equal counts but permuted payloads — the exact one-rank-
        pipelined shape — collapses into the single skew diagnosis
        instead of positional bucket-mismatch noise."""
        from paddle_tpu import static
        from paddle_tpu.core.dispatch import call_op

        def _prog(order):
            prog = static.Program()
            with static.program_guard(prog):
                g = static.data("grad", [4], "float32")
                out = g
                for name, nbytes in order:
                    def _c(v):
                        return v
                    _c._collective_axis = "dp"
                    _c._collective_nbytes = nbytes
                    out = call_op(_c, out, op_name=name)
                paddle.sum(out)
            return prog

        serial = _prog([("c_allgather", 512), ("c_reducescatter", 256)])
        piped = _prog([("c_reducescatter", 256), ("c_allgather", 512)])
        fs = analysis.check_collective_order([serial, piped],
                                             mesh_axes=("dp",))
        assert [f.rule for f in fs] == ["collective-schedule-skew"]
        # a genuinely divergent bucket layout stays a bucket finding
        other = _prog([("c_allgather", 999), ("c_reducescatter", 256)])
        fs = analysis.check_collective_order([serial, other],
                                             mesh_axes=("dp",))
        assert any(f.rule == "collective-order-mismatch" for f in fs)
        assert not any(f.rule == "collective-schedule-skew" for f in fs)

    def test_unknown_axis(self):
        p = self._rank_prog([("c_allreduce", "mp")])
        fs = analysis.check_collectives(p, mesh_axes=("dp",))
        assert any(f.rule == "unknown-collective-axis" and
                   f.severity == "error" for f in fs)

    def test_real_collective_lowering_is_stamped(self):
        """distributed.collective stamps _collective_axis on the traced
        lowerings so recorded programs carry a matchable axis."""
        import jax
        import paddle_tpu.distributed as dist
        from jax.sharding import PartitionSpec as P
        mesh = dist.make_mesh({"dp": jax.device_count()})
        grp = dist.new_group(axis_name="dp")

        def f(v):
            t = paddle.to_tensor(v)
            dist.all_reduce(t, group=grp)
            return t._value

        y = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("dp"),
                                  out_specs=P("dp")))(
            np.ones((jax.device_count(), 2), np.float32))
        assert float(np.asarray(y).sum()) == jax.device_count() ** 2 * 2


class TestPassDebugMode:
    def test_bad_pass_same_program(self):
        @static.register_pass("_test_identity_bad_pass")
        def _bad(prog):
            return prog  # contract violation: must be a NEW program

        prog, *_ = _simple_prog()
        prev = analysis.set_debug(True)
        try:
            with pytest.raises(analysis.VerifyError, match="new Program"):
                static.apply_pass(prog, "_test_identity_bad_pass")
        finally:
            analysis.set_debug(prev)
        # debug off: legacy behavior, pass output flows through
        assert static.apply_pass(prog, "_test_identity_bad_pass") is prog

    def test_broken_pass_output_raises(self):
        @static.register_pass("_test_breaker_pass")
        def _breaker(prog):
            return _shallow_clone(prog, prog.ops[1:])  # drops a producer

        prog, *_ = _simple_prog()
        prev = analysis.set_debug(True)
        try:
            with pytest.raises(analysis.VerifyError, match="use-before-def"):
                static.apply_pass(prog, "_test_breaker_pass")
        finally:
            analysis.set_debug(prev)

    def test_apply_pass_clears_compiled(self):
        @static.register_pass("_test_stale_cache_pass")
        def _stale(prog):
            p = _shallow_clone(prog, list(prog.ops))
            p._compiled = prog._compiled  # buggy pass shares the cache
            return p

        prog, *_, loss = _simple_prog()
        exe = static.Executor()
        exe.run(prog, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
        assert prog._compiled
        out = static.apply_pass(prog, "_test_stale_cache_pass")
        assert out._compiled == {}

    def test_debug_prune_verifies(self):
        prog, *_, loss = _simple_prog()
        prev = analysis.set_debug(True)
        try:
            pruned = static.prune(prog, [loss])
        finally:
            analysis.set_debug(prev)
        assert [op.name for op in pruned.ops] == ["matmul", "tanh", "mean"]

    def test_to_static_debug_verify(self):
        lin = nn.Linear(3, 3)
        prev = analysis.set_debug(True)
        try:
            sfn = paddle.jit.to_static(lambda v: lin(v).sum())
            out = sfn(paddle.to_tensor(np.ones((2, 3), np.float32)))
        finally:
            analysis.set_debug(prev)
        assert np.isfinite(float(np.asarray(out.numpy())))


class TestPruneSignature:
    def test_prune_filters_params_and_feeds(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            z = static.data("z", [2, 3], "float32")
            w = static.create_parameter([4, 3], "float32")
            w2 = static.create_parameter([3, 3], "float32")
            a = paddle.matmul(x, w)
            _b = paddle.matmul(z, w2)  # pruned branch
        pruned = static.prune(prog, [a])
        w_slot = prog._slot_of(w, create=False)
        w2_slot = prog._slot_of(w2, create=False)
        assert w_slot in pruned.params and w2_slot not in pruned.params
        assert "x" in pruned.feed_vars and "z" not in pruned.feed_vars
        # original program untouched
        assert "z" in prog.feed_vars and w2_slot in prog.params
        # the ORIGINAL full feed dict still runs (pruned feeds ignored);
        # a typo'd feed name still fails loudly
        exe = static.Executor()
        (got,) = exe.run(pruned,
                         feed={"x": np.ones((2, 4), np.float32),
                               "z": np.ones((2, 3), np.float32)},
                         fetch_list=[a])
        assert np.asarray(got).shape == (2, 3)
        with pytest.raises(KeyError):
            exe.run(pruned, feed={"nope": np.ones((2, 4), np.float32)},
                    fetch_list=[a])
        # the pruned program verifies clean, incl. feed/param coverage
        assert analysis.verify(pruned, targets=[a]) == []


class TestObservabilityExport:
    def test_findings_exported_as_counters(self):
        from paddle_tpu import monitor
        prog, *_ = _simple_prog()
        bad = _shallow_clone(prog, prog.ops[1:])
        analysis.verify(bad)
        stats = monitor.stats()
        key = 'analysis_findings{rule="use-before-def",severity="error"}'
        assert stats.get(key, 0) >= 1
        assert stats.get("analysis_runs", 0) >= 1
        from paddle_tpu.observability import export
        text = export.prometheus_text()
        assert 'paddle_tpu_analysis_findings{rule="use-before-def"' in text

    def test_per_op_dispatch_counters(self):
        import paddle_tpu.observability as obs
        from paddle_tpu import monitor
        obs.enable(categories=["dispatch"], dispatch_sample_rate=1.0)
        try:
            t = paddle.to_tensor(np.ones((2, 2), np.float32))
            paddle.tanh(t)
        finally:
            obs.disable()
        stats = monitor.stats()
        assert stats.get('dispatch_op_sampled{op="tanh"}', 0) >= 1
        assert stats.get('dispatch_op_ns{op="tanh"}', 0) >= 0


class TestSourceLint:
    def test_nondeterminism_in_traced(self, tmp_path):
        src = tmp_path / "mod.py"
        src.write_text(
            "import time\n"
            "import paddle_tpu as paddle\n\n"
            "@paddle.jit.to_static\n"
            "def step(x):\n"
            "    t0 = time.time()\n"
            "    return x * t0\n\n"
            "def eager(x):\n"
            "    return x * time.time()\n")
        fs = analysis.lint_source(paths=[str(src)],
                                  repo_root=str(tmp_path))
        assert len(fs) == 1  # only the traced fn is flagged
        assert fs[0].rule == "nondeterminism-in-traced"
        assert "mod.py:6" in fs[0].loc

    def test_eager_jnp_in_hot_path(self, tmp_path):
        rel = os.path.join("paddle_tpu", "core", "dispatch.py")
        target = tmp_path / rel
        target.parent.mkdir(parents=True)
        target.write_text(
            "import jax.numpy as jnp\n\n"
            "def call_op(fn, *args):\n"
            "    z = jnp.zeros((4,))\n"           # unguarded: flagged
            "    n = jnp.shape(args[0])\n"        # metadata-only: ok
            "    if enabled('dispatch'):\n"
            "        y = jnp.ones((4,))\n"        # guarded: ok
            "    return fn(z, n)\n")
        fs = analysis.lint_source(paths=[str(target)],
                                  repo_root=str(tmp_path))
        assert [f.rule for f in fs] == ["eager-jnp-in-hot-path"]
        assert "dispatch.py:4" in fs[0].loc

    def test_repo_hot_paths_clean(self):
        assert analysis.lint_source() == []


class TestConcurrencyLint:
    """The static half of the concurrency analyzer: one seeded defect
    per rule, Condition aliasing, call-site propagation, and the
    repo-wide sweep ending clean."""

    @staticmethod
    def _check(tmp_path, source, name="mod.py"):
        src = tmp_path / name
        src.write_text(source)
        return analysis.check_concurrency(paths=[str(src)],
                                          repo_root=str(tmp_path))

    def test_lock_order_cycle_ab_ba(self, tmp_path):
        """Seeded AB/BA: two functions take the same locks in opposite
        orders — the classic deadlock-by-interleaving."""
        fs = self._check(tmp_path, (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._cv = threading.Lock()\n"
            "    def a(self):\n"
            "        with self._mu:\n"
            "            with self._cv:\n"
            "                pass\n"
            "    def b(self):\n"
            "        with self._cv:\n"
            "            with self._mu:\n"
            "                pass\n"))
        cyc = [f for f in fs if f.rule == "lock-order-cycle"]
        assert cyc and cyc[0].severity == "error"
        assert "C._mu" in cyc[0].message and "C._cv" in cyc[0].message

    def test_lock_order_cycle_across_call_sites(self, tmp_path):
        """The edge hides behind a call: a() holds mu and CALLS helper()
        which takes cv; b() nests them the other way."""
        fs = self._check(tmp_path, (
            "import threading\n"
            "class C:\n"
            "    def helper(self):\n"
            "        with self._cv:\n"
            "            return 1\n"
            "    def a(self):\n"
            "        with self._mu:\n"
            "            self.helper()\n"
            "    def b(self):\n"
            "        with self._cv:\n"
            "            with self._mu:\n"
            "                pass\n"))
        assert any(f.rule == "lock-order-cycle" and f.severity == "error"
                   for f in fs)

    def test_consistent_order_clean(self, tmp_path):
        fs = self._check(tmp_path, (
            "import threading\n"
            "class C:\n"
            "    def a(self):\n"
            "        with self._mu:\n"
            "            with self._cv:\n"
            "                pass\n"
            "    def b(self):\n"
            "        with self._mu:\n"
            "            with self._cv:\n"
            "                pass\n"))
        assert not [f for f in fs if f.rule == "lock-order-cycle"]

    def test_blocking_call_under_lock(self, tmp_path):
        fs = self._check(tmp_path, (
            "class C:\n"
            "    def pull(self, keys):\n"
            "        with self._mu:\n"
            "            return self.client.pull_sparse(0, keys)\n"))
        hits = [f for f in fs if f.rule == "blocking-call-under-lock"]
        assert hits and hits[0].severity == "warning"
        assert "C._mu" in hits[0].message

    def test_blocking_call_propagates_through_calls(self, tmp_path):
        """A blocking leaf buried two calls deep still surfaces at the
        locked call site (the *_locked-helper pattern)."""
        fs = self._check(tmp_path, (
            "import time\n"
            "class C:\n"
            "    def _emit_locked(self):\n"
            "        self._log()\n"
            "    def _log(self):\n"
            "        time.sleep(1)\n"
            "    def tick(self):\n"
            "        with self._mu:\n"
            "            self._emit_locked()\n"))
        hits = [f for f in fs if f.rule == "blocking-call-under-lock"]
        assert hits and "sleep" in hits[0].message

    def test_cv_wait_on_held_lock_exempt(self, tmp_path):
        """Condition.wait on the condition over the HELD lock releases
        it — that is not blocking-under-lock; and with a while-loop +
        timeout it is fully clean."""
        fs = self._check(tmp_path, (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._cv = threading.Condition(self._mu)\n"
            "    def drain(self):\n"
            "        with self._cv:\n"
            "            while self._rows:\n"
            "                self._cv.wait(timeout=0.2)\n"
            "            self._cv.notify_all()\n"))
        assert [f for f in fs if f.severity != "info"] == []

    def test_cond_wait_outside_loop_and_without_timeout(self, tmp_path):
        fs = self._check(tmp_path, (
            "class C:\n"
            "    def wait_once(self):\n"
            "        with self._cv:\n"
            "            self._cv.wait()\n"))
        rules = {f.rule for f in fs}
        assert "cond-wait-outside-loop" in rules
        assert "cond-wait-without-timeout" in rules

    def test_notify_without_lock(self, tmp_path):
        fs = self._check(tmp_path, (
            "class C:\n"
            "    def poke(self):\n"
            "        self._cv.notify_all()\n"))
        hits = [f for f in fs if f.rule == "notify-without-lock"]
        assert hits and hits[0].severity == "error"
        # the *_locked naming convention asserts the caller holds it
        fs2 = self._check(tmp_path, (
            "class C:\n"
            "    def _poke_locked(self):\n"
            "        self._cv.notify_all()\n"), name="mod2.py")
        assert not [f for f in fs2 if f.rule == "notify-without-lock"]

    def test_condition_alias_notify_clean(self, tmp_path):
        """notify on a Condition built over the held lock is legal —
        the aliasing must resolve."""
        fs = self._check(tmp_path, (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._cv = threading.Condition(self._mu)\n"
            "    def poke(self):\n"
            "        with self._mu:\n"
            "            self._cv.notify_all()\n"))
        assert not [f for f in fs if f.rule == "notify-without-lock"]

    def test_suppression_comment_demotes_to_info(self, tmp_path):
        fs = self._check(tmp_path, (
            "class C:\n"
            "    def pull(self, keys):\n"
            "        # lint: blocking-call-under-lock wire framing is "
            "serialized by design\n"
            "        with self._mu:\n"
            "            return self.client.pull_sparse(0, keys)\n"))
        hits = [f for f in fs if f.rule == "blocking-call-under-lock"]
        assert hits and hits[0].severity == "info"
        assert "wire framing" in hits[0].message
        # prefix token matches the whole rule family
        fs2 = self._check(tmp_path, (
            "class C:\n"
            "    def a(self):\n"
            "        with self._mu:\n"
            "            # lint: lock-order deliberate nesting, see b()\n"
            "            with self._cv:\n"
            "                pass\n"
            "    def b(self):\n"
            "        with self._cv:\n"
            "            with self._mu:\n"
            "                pass\n"), name="mod3.py")
        cyc = [f for f in fs2 if f.rule == "lock-order-cycle"]
        assert cyc and cyc[0].severity == "info"

    def test_repo_concurrency_sweep_clean(self):
        """The acceptance anchor: the default sweep over the thread-
        heavy runtime modules has ZERO unsuppressed findings — every
        deliberate case carries its auditable reason."""
        fs = analysis.check_concurrency()
        live = [f for f in fs if f.severity != "info"]
        assert live == [], "\n".join(repr(f) for f in live)
        # the suppressions that remain are real and carry reasons
        assert all("suppressed (" in f.message for f in fs
                   if f.severity == "info")


class TestLintRuleRouting:
    """lint.py rule interaction: default-sweep path routing (a file
    reached only via BARRIER/RESPAWN paths gets only the multi-process
    rules; REMAT paths get only the remat rule) and suppression-comment
    interaction with the lint_source families."""

    BARRIER_SRC = (
        "import time\n"
        "import subprocess\n"
        "def sync(pod):\n"
        "    pod.barrier('step')\n"          # barrier-without-timeout
        "def keep_alive(cmd):\n"
        "    while True:\n"                  # respawn-without-backoff
        "        p = subprocess.Popen(cmd)\n"
        "        p.wait()\n"
        "def retry(sock, msg):\n"
        "    while True:\n"                  # retry-without-backoff
        "        try:\n"
        "            sock.sendall(msg)\n"
        "            return\n"
        "        except OSError:\n"
        "            pass\n")

    def test_barrier_respawn_path_routing(self, tmp_path, monkeypatch):
        # the module is shadowed by the lint() function on the package
        lint_mod = sys.modules["paddle_tpu.analysis.lint"]
        d = tmp_path / "paddle_tpu" / "distributed"
        d.mkdir(parents=True)
        (d / "newmod.py").write_text(self.BARRIER_SRC)
        monkeypatch.setattr(lint_mod, "BARRIER_PATHS",
                            (os.path.join("paddle_tpu", "distributed"),))
        monkeypatch.setattr(lint_mod, "RESPAWN_PATHS",
                            (os.path.join("paddle_tpu", "distributed"),))
        monkeypatch.setattr(lint_mod, "RPC_PATHS", ())
        monkeypatch.setattr(lint_mod, "SPAN_PATHS", ())
        monkeypatch.setattr(lint_mod, "REMAT_PATHS", ())
        monkeypatch.setattr(lint_mod, "HOT_PATHS", {})
        fs = lint_mod.lint_source(repo_root=str(tmp_path))
        rules = {f.rule for f in fs}
        # reached ONLY via BARRIER/RESPAWN paths: the two multi-process
        # rules fire, the full-rule families (retry loops) do NOT
        assert "barrier-without-timeout" in rules
        assert "respawn-without-backoff" in rules
        assert "retry-without-backoff" not in rules
        # registered as an RPC path too -> the retry rule now fires
        monkeypatch.setattr(
            lint_mod, "RPC_PATHS",
            (os.path.join("paddle_tpu", "distributed", "newmod.py"),))
        fs = lint_mod.lint_source(repo_root=str(tmp_path))
        assert "retry-without-backoff" in {f.rule for f in fs}

    def test_remat_path_routing(self, tmp_path, monkeypatch):
        lint_mod = sys.modules["paddle_tpu.analysis.lint"]
        d = tmp_path / "paddle_tpu" / "models"
        d.mkdir(parents=True)
        (d / "m.py").write_text(
            "import jax\n"
            "import time\n"
            "def block(fn, x):\n"
            "    t0 = time.time()\n"
            "    return jax.checkpoint(fn)(x), t0\n")
        monkeypatch.setattr(lint_mod, "REMAT_PATHS",
                            (os.path.join("paddle_tpu", "models"),))
        for const in ("BARRIER_PATHS", "RESPAWN_PATHS", "RPC_PATHS",
                      "SPAN_PATHS"):
            monkeypatch.setattr(lint_mod, const, ())
        monkeypatch.setattr(lint_mod, "HOT_PATHS", {})
        fs = lint_mod.lint_source(repo_root=str(tmp_path))
        rules = {f.rule for f in fs}
        # remat-only routing: the remat rule fires, nothing else does
        assert rules == {"raw-remat-outside-policy"}

    def test_lint_source_suppression(self, tmp_path):
        src = tmp_path / "m.py"
        src.write_text(
            "def sync(pod):\n"
            "    # lint: barrier-without-timeout deadline injected by "
            "the caller's harness\n"
            "    pod.barrier('step')\n"
            "def sync2(pod):\n"
            "    pod.barrier('step2')\n")
        fs = analysis.lint_source(paths=[str(src)],
                                  repo_root=str(tmp_path))
        hits = [f for f in fs if f.rule == "barrier-without-timeout"]
        assert len(hits) == 2
        by_sev = {f.severity for f in hits}
        assert by_sev == {"info", "warning"}  # one suppressed, one live
        info = next(f for f in hits if f.severity == "info")
        assert "deadline injected" in info.message


class TestLockwatch:
    """The dynamic half: AB/BA cycle detection through the flight dump
    (the tier-1 acceptance case), disarmed-factory rawness, contention
    accounting, and held-set introspection."""

    def teardown_method(self, method):
        from paddle_tpu.analysis import lockwatch
        from paddle_tpu.observability import flight
        lockwatch.disable()
        lockwatch.reset()
        flight.uninstall()

    def test_disarmed_factories_are_raw_primitives(self):
        import threading
        from paddle_tpu.analysis import lockwatch
        assert not lockwatch.enabled()
        assert type(lockwatch.Lock()) is type(threading.Lock())
        assert type(lockwatch.RLock()) is type(threading.RLock())
        assert isinstance(lockwatch.Condition(), threading.Condition)

    def test_ab_ba_cycle_reported_through_flight_dump(self, tmp_path):
        """Synthetic AB/BA: the watchdog detects the order cycle ONLINE
        (no actual deadlock needed), counts it, and dumps the edge graph
        + holder stacks through the flight recorder."""
        import json
        from paddle_tpu import monitor
        from paddle_tpu.analysis import lockwatch
        from paddle_tpu.observability import flight
        lockwatch.enable()
        lockwatch.reset()
        flight.install(str(tmp_path))
        before = monitor.stats().get("lockwatch_order_violations_total", 0)
        a = lockwatch.Lock("tier1.A")
        b = lockwatch.Lock("tier1.B")
        with a:
            with b:
                assert lockwatch.held_names() == ["tier1.A", "tier1.B"]
        with b:
            with a:  # the reversed order closes the cycle
                pass
        v = lockwatch.violations()
        assert v and v[0]["cycle"] == ["tier1.B", "tier1.A", "tier1.B"]
        assert monitor.stats()["lockwatch_order_violations_total"] \
            == before + 1
        path = flight.latest_dump()
        assert path is not None
        rec = json.load(open(path))
        assert rec["reason"] == "lock_order_violation"
        lw = rec["lockwatch"]
        assert lw["violations"][0]["cycle"] == \
            ["tier1.B", "tier1.A", "tier1.B"]
        # holder stacks: every edge of the cycle carries the stack that
        # first took that order
        stacks = lw["violations"][0]["stacks"]
        assert set(stacks) == {"tier1.A->tier1.B", "tier1.B->tier1.A"}
        assert all(s["stack"] for s in stacks.values())

    def test_every_flight_dump_carries_lockwatch_section(self, tmp_path):
        """Any dump while armed (incl. reason='pod_failure') shows the
        held sets — the post-mortem knows who held what at death."""
        import json
        from paddle_tpu.analysis import lockwatch
        from paddle_tpu.observability import flight
        lockwatch.enable()
        lockwatch.reset()
        flight.install(str(tmp_path))
        mu = lockwatch.Lock("pod.fake")
        with mu:
            p = flight.dump("pod_failure",
                            extra={"pod_failure": {"gen": 0}})
        rec = json.load(open(p))
        assert rec["lockwatch"]["enabled"]
        held = rec["lockwatch"]["held"]
        assert any("pod.fake" in names for names in held.values())

    def test_contention_ns_counter(self):
        import threading
        import time as _t
        from paddle_tpu import monitor
        from paddle_tpu.analysis import lockwatch
        lockwatch.enable()
        mu = lockwatch.Lock("contended.mu")
        def hold():
            with mu:
                _t.sleep(0.1)
        t = threading.Thread(target=hold)
        t.start()
        _t.sleep(0.02)
        with mu:
            pass
        t.join()
        key = 'lockwatch_contention_ns{lock="contended.mu"}'
        assert monitor.stats().get(key, 0) > 10_000_000  # blocked >10ms

    def test_rlock_reentry_no_self_edge(self):
        from paddle_tpu.analysis import lockwatch
        lockwatch.enable()
        lockwatch.reset()
        r = lockwatch.RLock("re.mu")
        with r:
            with r:
                assert lockwatch.held_names() == ["re.mu"]
        assert lockwatch.held_names() == []
        assert lockwatch.snapshot()["edges"] == []


class TestPodLockDiscipline:
    """Regression for the straggler-sweep fix: telemetry (run-log +
    gauges) must be emitted with the coordinator condition RELEASED —
    verified with the lockwatch held-set, which is exactly what caught
    the original hazard."""

    def test_straggler_telemetry_emitted_outside_coordinator_lock(
            self, tmp_path, monkeypatch):
        import time as _t
        from paddle_tpu.analysis import lockwatch
        from paddle_tpu.distributed.pod import PodCoordinator
        from paddle_tpu.observability import runlog
        import threading
        prev = lockwatch.enable()
        coord = None
        try:
            lockwatch.reset()
            coord = PodCoordinator(expected=2, lease_ttl=30.0,
                                   monitor_interval=3600.0,
                                   straggler_threshold=0.05)
            # serve_forever on a thread so close() (which blocks on the
            # serve loop acknowledging shutdown) can complete
            threading.Thread(target=coord.serve_forever,
                             daemon=True).start()
            now = _t.time()
            with coord._cond:
                coord._members = {0: {"origin": 0}, 1: {"origin": 1}}
                # rank 1's lease is past the straggler threshold but
                # inside the ttl: the next sweep must announce it
                coord._leases = {0: now, 1: now - 1.0}
            held_at_emit = []
            orig_event = runlog.event
            def spy(what, **fields):
                if what == "pod_straggler":
                    held_at_emit.append(list(lockwatch.held_names()))
                return orig_event(what, **fields)
            monkeypatch.setattr(runlog, "event", spy)
            import paddle_tpu.distributed.pod as pod_mod
            monkeypatch.setattr(pod_mod, "_runlog_event",
                                lambda what, **f: spy(what, **f))
            coord._monitor_once(_t.time())
            assert held_at_emit, "straggler event never fired"
            assert all("pod.coordinator" not in held
                       for held in held_at_emit), held_at_emit
            # ...and the straggler IS tracked (behavior preserved)
            assert coord.stragglers() == [1]
        finally:
            if coord is not None:
                coord.close()
            if not prev:
                lockwatch.disable()
            lockwatch.reset()

    def test_writeback_worker_pushes_outside_queue_lock(self):
        """async_cache discipline pin: the pass surfaced NO defects in
        the write-back queue — this locks that in at runtime: the
        worker's wire push must run with the queue lock released (a
        push under wbq.mu would stall every producer behind a slow
        PS)."""
        import numpy as np
        from paddle_tpu.analysis import lockwatch
        from paddle_tpu.distributed.ps.async_cache import WriteBackQueue
        prev = lockwatch.enable()
        try:
            lockwatch.reset()
            held_at_push = []
            class _Client:
                def push_sparse_delta(self, table, keys, deltas):
                    held_at_push.append(list(lockwatch.held_names()))
            q = WriteBackQueue(_Client())
            q.put(7, np.array([1, 2, 3], np.uint64),
                  np.ones((3, 4), np.float32))
            q.flush(timeout=10.0)
            q.stop()
            assert held_at_push, "push never reached the client"
            assert all(not any(n.startswith("wbq.") for n in held)
                       for held in held_at_push), held_at_push
        finally:
            if not prev:
                lockwatch.disable()
            lockwatch.reset()


class TestLadderAndCLI:
    def test_ladder_verifies_clean(self):
        fs, summary = analysis.ladder.verify_ladder()
        assert fs == []
        assert set(summary) == {"resnet", "gpt", "bert", "detection",
                                "hbm_cache", "ctr", "remat", "serving",
                                "allreduce", "zero1", "zero3",
                                "zero3_prefetch"}

    def test_cli_source_mode(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint_program.py"),
             "--source"], capture_output=True, text=True, cwd=REPO,
            timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stdout + r.stderr
        assert "0 error(s)" in r.stdout

    def test_cli_concurrency_mode(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint_program.py"),
             "--concurrency"], capture_output=True, text=True, cwd=REPO,
            timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stdout + r.stderr
        assert "0 error(s), 0 warning(s)" in r.stdout
        # the deliberate suppressions print as auditable INFO findings
        assert "suppressed (" in r.stdout

    @pytest.mark.slow
    def test_cli_ladder_mode(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint_program.py"),
             "--ladder"], capture_output=True, text=True, cwd=REPO,
            timeout=600, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stdout + r.stderr
        assert "0 error(s), 0 warning(s)" in r.stdout


class TestCrossEntropyLabelSemantics:
    def test_soft_label_gets_no_grad(self):
        """Label threads through dispatch as a slot (static coverage) but
        keeps the reference's no-@GRAD contract: gradients must not flow
        into a live soft-label branch."""
        t = paddle.to_tensor(np.ones((2, 3), np.float32) * 0.3,
                             stop_gradient=False)
        probs = nn.functional.softmax(t)
        logits = paddle.to_tensor(
            np.random.RandomState(0).rand(2, 3).astype(np.float32),
            stop_gradient=False)
        loss = nn.functional.cross_entropy(logits, probs, soft_label=True)
        loss.backward()
        assert logits.grad is not None
        assert t.grad is None or float(np.abs(np.asarray(
            t.grad.numpy())).sum()) == 0.0

    def test_label_recorded_as_feed_slot(self):
        """The static-recording half of the same fix: the label feed must
        be a live program input, not a baked build-time constant."""
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            y = static.data("y", [2], "int64")
            w = static.create_parameter([4, 3], "float32")
            loss = nn.functional.cross_entropy(paddle.matmul(x, w), y)
        assert analysis.verify(prog, targets=[loss]) == []  # no unused-feed
        exe = static.Executor()
        feed_x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        (l0,) = exe.run(prog, feed={"x": feed_x,
                                    "y": np.array([0, 0], np.int64)},
                        fetch_list=[loss])
        (l1,) = exe.run(prog, feed={"x": feed_x,
                                    "y": np.array([2, 2], np.int64)},
                        fetch_list=[loss])
        assert not np.allclose(np.asarray(l0), np.asarray(l1))
