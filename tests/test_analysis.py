"""paddle_tpu.analysis — program verifier, dtype checker, donation/collective
hazard detection, lint, and the debug-mode pass hooks.

The five seeded defect classes the verifier must catch (ISSUE 3 acceptance):
use-before-def, dtype drift, donated-slot reuse, collective-order mismatch,
dangling buffer update.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.analysis as analysis
from paddle_tpu import nn, static
from paddle_tpu.core.dispatch import call_op
from paddle_tpu.static.passes import _shallow_clone
from paddle_tpu.static.program import _OpRecord, _Slot

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _simple_prog():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 4], "float32")
        w = static.create_parameter([4, 3], "float32")
        h = paddle.matmul(x, w)
        y = paddle.tanh(h)
        loss = paddle.mean(y)
    return prog, x, w, y, loss


def _bn_prog():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 4, 3, 3], "float32")
        bn = nn.BatchNorm2D(4)
        y = bn(x)
        loss = paddle.mean(y)
    return prog, bn, loss


def _rules(findings):
    return {f.rule for f in findings}


class TestGraphVerifier:
    def test_clean_program_no_findings(self):
        prog, *_, loss = _simple_prog()
        assert analysis.verify(prog, targets=[loss]) == []

    def test_use_before_def(self):
        """Seeded defect 1: a broken rewrite drops a producer."""
        prog, *_ = _simple_prog()
        bad = _shallow_clone(prog, prog.ops[1:])  # tanh now reads a ghost
        fs = analysis.verify(bad)
        assert "use-before-def" in _rules(fs)
        assert any(f.severity == "error" for f in fs)
        with pytest.raises(analysis.VerifyError, match="use-before-def"):
            analysis.verify(bad, raise_on_error=True)

    def test_duplicate_slot_write(self):
        prog, *_ = _simple_prog()
        dup = prog.ops[1]
        bad = _shallow_clone(prog, list(prog.ops) + [
            _OpRecord(dup.fn, dup.arg_slots, dup.kwarg_slots,
                      dup.out_slots, dup.name)])
        fs = analysis.check_graph(bad)
        assert any(f.rule == "duplicate-slot-write" and
                   f.severity == "error" for f in fs)

    def test_dangling_buffer_update(self):
        """Seeded defect 5: stat-update producer dropped but the buffer
        alias kept (what a forgetful pass does)."""
        prog, _bn, _loss = _bn_prog()
        assert prog._buffer_updates  # the BN program records the aliases
        bad = _shallow_clone(prog, [op for op in prog.ops
                                    if op.name != "batch_norm_stat_update"])
        fs = analysis.verify(bad)
        assert "dangling-buffer-update" in _rules(fs)
        # the real pass (and prune) filter the aliases: clean
        good = static.apply_pass(prog, "remove_stat_update_pass")
        assert "dangling-buffer-update" not in _rules(analysis.verify(good))

    def test_dead_op_needs_targets(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            a = paddle.tanh(x)
            b = paddle.mean(a)
            c = paddle.exp(x)      # dead for fetch=b
            _d = paddle.sum(c)
        fs = analysis.verify(prog, targets=[b])
        dead = [f for f in fs if f.rule == "dead-op"]
        assert {f.op_name for f in dead} == {"exp", "sum"}
        # without a fetch set dead-ness is undecidable: no dead findings
        assert "dead-op" not in _rules(analysis.verify(prog))

    def test_unused_inputs_flagged(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            _unused = static.data("y", [2], "int64")
            w = static.create_parameter([4, 3], "float32")
            paddle.matmul(x, w)
        # a param slot no kept op references — the pre-fix prune() left
        # every original input in the signature like this
        w2 = static.create_parameter([3, 3], "float32")
        prog._slot_of(w2)
        rules = _rules(analysis.check_graph(prog))
        assert "unused-feed" in rules
        assert "unused-program-input" in rules


class TestDtypeChecker:
    def test_amp_boundary_drift(self):
        """Seeded defect 2 (dtype drift): a layer_norm-class op eats bf16
        but returns fp32 — the missing AMP output downcast."""
        import jax.numpy as jnp
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "bfloat16")
            call_op(lambda v: jnp.asarray(v, jnp.float32),
                    x, op_name="layer_norm")
        fs = analysis.check_dtypes(prog)
        assert any(f.rule == "amp-boundary-upcast" and
                   f.op_name == "layer_norm" for f in fs)

    def test_mixed_precision_matmul(self):
        import jax.numpy as jnp
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "bfloat16")
            w = static.create_parameter([4, 3], "float32")  # master leak
            call_op(lambda a, b: jnp.matmul(a, b.astype(a.dtype)),
                    x, w, op_name="matmul")
        fs = analysis.check_dtypes(prog)
        assert any(f.rule == "mixed-precision-input" for f in fs)

    def test_shape_specialization(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [-1, 4], "float32")
            y = paddle.reshape(x, [1, 4])  # bakes the dynamic batch
            paddle.mean(y)
        fs = analysis.check_dtypes(prog)
        assert any(f.rule == "shape-specialization" and
                   f.severity == "error" for f in fs)

    def test_polymorphic_program_clean(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [-1, 4], "float32")
            paddle.mean(paddle.tanh(x))
        assert analysis.check_dtypes(prog) == []


class TestDonation:
    def test_donated_buffer_alias_read(self):
        """Seeded defect 3 (donated-slot reuse): when the BN buffers ride a
        donated carry, the normalize op's read AFTER the stat update is a
        stale-buffer read."""
        prog, _bn, _loss = _bn_prog()
        donated = set(prog._buffer_updates)
        fs = analysis.check_donation(prog, donated=donated)
        assert fs and all(f.rule == "donated-slot-reuse" for f in fs)
        # without donation the write-back is deferred: the same read is
        # legal (the executor assigns buffers after the run)
        assert analysis.check_donation(prog, donated=set()) == []

    def test_donated_input_overwrite(self):
        prog, x, w, *_ = _simple_prog()
        w_slot = prog._slot_of(w, create=False)
        x_slot = prog._slot_of(x, create=False)
        bad = _shallow_clone(prog, list(prog.ops) + [
            _OpRecord(lambda v: v, [_Slot(x_slot)], {}, [w_slot], "assign")])
        fs = analysis.check_donation(bad, donated={w_slot})
        assert any(f.rule == "donated-slot-reuse" for f in fs)
        # the graph verifier independently warns on the input overwrite
        assert any(f.rule == "input-overwrite"
                   for f in analysis.check_graph(bad))

    def test_static_function_partition(self):
        lin = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(parameters=lin.parameters(),
                                   learning_rate=0.1)

        def step(xb):
            loss = lin(xb).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        sfn = paddle.jit.to_static(step)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        sfn(x)
        assert analysis.errors(sfn.verify()) == []
        # seeded hazard: a donated uid also threaded read-only
        donated = sfn._last_partition["donated"]
        assert donated
        sfn._last_partition["readonly"] = list(
            sfn._last_partition["readonly"]) + [donated[0]]
        bad = sfn.verify()
        assert any(f.rule == "donated-slot-reuse" and f.severity == "error"
                   for f in bad)


class TestCollectives:
    @staticmethod
    def _rank_prog(seq):
        prog = static.Program()
        with static.program_guard(prog):
            g = static.data("grad", [4], "float32")
            out = g
            for name, ax in seq:
                def _c(v):
                    return v
                _c._collective_axis = ax
                out = call_op(_c, out, op_name=name)
            paddle.sum(out)
        return prog

    def test_order_mismatch(self):
        """Seeded defect 4: ranks disagree on the collective schedule."""
        p0 = self._rank_prog([("c_allreduce", "dp"), ("c_broadcast", "dp")])
        p1 = self._rank_prog([("c_broadcast", "dp"), ("c_allreduce", "dp")])
        fs = analysis.check_collective_order([p0, p1], mesh_axes=("dp",))
        assert any(f.rule == "collective-order-mismatch" and
                   f.severity == "error" for f in fs)
        # axis skew at the same position is also a mismatch
        p2 = self._rank_prog([("c_allreduce", "mp"), ("c_broadcast", "dp")])
        fs = analysis.check_collective_order([p0, p2],
                                             mesh_axes=("dp", "mp"))
        assert any(f.rule == "collective-order-mismatch" for f in fs)
        # length skew deadlocks too
        p3 = self._rank_prog([("c_allreduce", "dp")])
        fs = analysis.check_collective_order([p0, p3], mesh_axes=("dp",))
        assert any("deadlock" in f.message for f in fs)

    def test_matching_ranks_clean(self):
        seq = [("c_allreduce", "dp"), ("c_broadcast", "dp")]
        progs = [self._rank_prog(seq), self._rank_prog(seq)]
        assert analysis.check_collective_order(progs,
                                               mesh_axes=("dp",)) == []

    def test_unknown_axis(self):
        p = self._rank_prog([("c_allreduce", "mp")])
        fs = analysis.check_collectives(p, mesh_axes=("dp",))
        assert any(f.rule == "unknown-collective-axis" and
                   f.severity == "error" for f in fs)

    def test_real_collective_lowering_is_stamped(self):
        """distributed.collective stamps _collective_axis on the traced
        lowerings so recorded programs carry a matchable axis."""
        import jax
        import paddle_tpu.distributed as dist
        from jax.sharding import PartitionSpec as P
        mesh = dist.make_mesh({"dp": jax.device_count()})
        grp = dist.new_group(axis_name="dp")

        def f(v):
            t = paddle.to_tensor(v)
            dist.all_reduce(t, group=grp)
            return t._value

        y = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("dp"),
                                  out_specs=P("dp")))(
            np.ones((jax.device_count(), 2), np.float32))
        assert float(np.asarray(y).sum()) == jax.device_count() ** 2 * 2


class TestPassDebugMode:
    def test_bad_pass_same_program(self):
        @static.register_pass("_test_identity_bad_pass")
        def _bad(prog):
            return prog  # contract violation: must be a NEW program

        prog, *_ = _simple_prog()
        prev = analysis.set_debug(True)
        try:
            with pytest.raises(analysis.VerifyError, match="new Program"):
                static.apply_pass(prog, "_test_identity_bad_pass")
        finally:
            analysis.set_debug(prev)
        # debug off: legacy behavior, pass output flows through
        assert static.apply_pass(prog, "_test_identity_bad_pass") is prog

    def test_broken_pass_output_raises(self):
        @static.register_pass("_test_breaker_pass")
        def _breaker(prog):
            return _shallow_clone(prog, prog.ops[1:])  # drops a producer

        prog, *_ = _simple_prog()
        prev = analysis.set_debug(True)
        try:
            with pytest.raises(analysis.VerifyError, match="use-before-def"):
                static.apply_pass(prog, "_test_breaker_pass")
        finally:
            analysis.set_debug(prev)

    def test_apply_pass_clears_compiled(self):
        @static.register_pass("_test_stale_cache_pass")
        def _stale(prog):
            p = _shallow_clone(prog, list(prog.ops))
            p._compiled = prog._compiled  # buggy pass shares the cache
            return p

        prog, *_, loss = _simple_prog()
        exe = static.Executor()
        exe.run(prog, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
        assert prog._compiled
        out = static.apply_pass(prog, "_test_stale_cache_pass")
        assert out._compiled == {}

    def test_debug_prune_verifies(self):
        prog, *_, loss = _simple_prog()
        prev = analysis.set_debug(True)
        try:
            pruned = static.prune(prog, [loss])
        finally:
            analysis.set_debug(prev)
        assert [op.name for op in pruned.ops] == ["matmul", "tanh", "mean"]

    def test_to_static_debug_verify(self):
        lin = nn.Linear(3, 3)
        prev = analysis.set_debug(True)
        try:
            sfn = paddle.jit.to_static(lambda v: lin(v).sum())
            out = sfn(paddle.to_tensor(np.ones((2, 3), np.float32)))
        finally:
            analysis.set_debug(prev)
        assert np.isfinite(float(np.asarray(out.numpy())))


class TestPruneSignature:
    def test_prune_filters_params_and_feeds(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            z = static.data("z", [2, 3], "float32")
            w = static.create_parameter([4, 3], "float32")
            w2 = static.create_parameter([3, 3], "float32")
            a = paddle.matmul(x, w)
            _b = paddle.matmul(z, w2)  # pruned branch
        pruned = static.prune(prog, [a])
        w_slot = prog._slot_of(w, create=False)
        w2_slot = prog._slot_of(w2, create=False)
        assert w_slot in pruned.params and w2_slot not in pruned.params
        assert "x" in pruned.feed_vars and "z" not in pruned.feed_vars
        # original program untouched
        assert "z" in prog.feed_vars and w2_slot in prog.params
        # the ORIGINAL full feed dict still runs (pruned feeds ignored);
        # a typo'd feed name still fails loudly
        exe = static.Executor()
        (got,) = exe.run(pruned,
                         feed={"x": np.ones((2, 4), np.float32),
                               "z": np.ones((2, 3), np.float32)},
                         fetch_list=[a])
        assert np.asarray(got).shape == (2, 3)
        with pytest.raises(KeyError):
            exe.run(pruned, feed={"nope": np.ones((2, 4), np.float32)},
                    fetch_list=[a])
        # the pruned program verifies clean, incl. feed/param coverage
        assert analysis.verify(pruned, targets=[a]) == []


class TestObservabilityExport:
    def test_findings_exported_as_counters(self):
        from paddle_tpu import monitor
        prog, *_ = _simple_prog()
        bad = _shallow_clone(prog, prog.ops[1:])
        analysis.verify(bad)
        stats = monitor.stats()
        key = 'analysis_findings{rule="use-before-def",severity="error"}'
        assert stats.get(key, 0) >= 1
        assert stats.get("analysis_runs", 0) >= 1
        from paddle_tpu.observability import export
        text = export.prometheus_text()
        assert 'paddle_tpu_analysis_findings{rule="use-before-def"' in text

    def test_per_op_dispatch_counters(self):
        import paddle_tpu.observability as obs
        from paddle_tpu import monitor
        obs.enable(categories=["dispatch"], dispatch_sample_rate=1.0)
        try:
            t = paddle.to_tensor(np.ones((2, 2), np.float32))
            paddle.tanh(t)
        finally:
            obs.disable()
        stats = monitor.stats()
        assert stats.get('dispatch_op_sampled{op="tanh"}', 0) >= 1
        assert stats.get('dispatch_op_ns{op="tanh"}', 0) >= 0


class TestSourceLint:
    def test_nondeterminism_in_traced(self, tmp_path):
        src = tmp_path / "mod.py"
        src.write_text(
            "import time\n"
            "import paddle_tpu as paddle\n\n"
            "@paddle.jit.to_static\n"
            "def step(x):\n"
            "    t0 = time.time()\n"
            "    return x * t0\n\n"
            "def eager(x):\n"
            "    return x * time.time()\n")
        fs = analysis.lint_source(paths=[str(src)],
                                  repo_root=str(tmp_path))
        assert len(fs) == 1  # only the traced fn is flagged
        assert fs[0].rule == "nondeterminism-in-traced"
        assert "mod.py:6" in fs[0].loc

    def test_eager_jnp_in_hot_path(self, tmp_path):
        rel = os.path.join("paddle_tpu", "core", "dispatch.py")
        target = tmp_path / rel
        target.parent.mkdir(parents=True)
        target.write_text(
            "import jax.numpy as jnp\n\n"
            "def call_op(fn, *args):\n"
            "    z = jnp.zeros((4,))\n"           # unguarded: flagged
            "    n = jnp.shape(args[0])\n"        # metadata-only: ok
            "    if enabled('dispatch'):\n"
            "        y = jnp.ones((4,))\n"        # guarded: ok
            "    return fn(z, n)\n")
        fs = analysis.lint_source(paths=[str(target)],
                                  repo_root=str(tmp_path))
        assert [f.rule for f in fs] == ["eager-jnp-in-hot-path"]
        assert "dispatch.py:4" in fs[0].loc

    def test_repo_hot_paths_clean(self):
        assert analysis.lint_source() == []


class TestLadderAndCLI:
    def test_ladder_verifies_clean(self):
        fs, summary = analysis.ladder.verify_ladder()
        assert fs == []
        assert set(summary) == {"resnet", "gpt", "bert", "detection",
                                "hbm_cache", "ctr", "remat", "serving",
                                "allreduce", "zero1", "zero3"}

    def test_cli_source_mode(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint_program.py"),
             "--source"], capture_output=True, text=True, cwd=REPO,
            timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stdout + r.stderr
        assert "0 error(s)" in r.stdout

    @pytest.mark.slow
    def test_cli_ladder_mode(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint_program.py"),
             "--ladder"], capture_output=True, text=True, cwd=REPO,
            timeout=600, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stdout + r.stderr
        assert "0 error(s), 0 warning(s)" in r.stdout


class TestCrossEntropyLabelSemantics:
    def test_soft_label_gets_no_grad(self):
        """Label threads through dispatch as a slot (static coverage) but
        keeps the reference's no-@GRAD contract: gradients must not flow
        into a live soft-label branch."""
        t = paddle.to_tensor(np.ones((2, 3), np.float32) * 0.3,
                             stop_gradient=False)
        probs = nn.functional.softmax(t)
        logits = paddle.to_tensor(
            np.random.RandomState(0).rand(2, 3).astype(np.float32),
            stop_gradient=False)
        loss = nn.functional.cross_entropy(logits, probs, soft_label=True)
        loss.backward()
        assert logits.grad is not None
        assert t.grad is None or float(np.abs(np.asarray(
            t.grad.numpy())).sum()) == 0.0

    def test_label_recorded_as_feed_slot(self):
        """The static-recording half of the same fix: the label feed must
        be a live program input, not a baked build-time constant."""
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            y = static.data("y", [2], "int64")
            w = static.create_parameter([4, 3], "float32")
            loss = nn.functional.cross_entropy(paddle.matmul(x, w), y)
        assert analysis.verify(prog, targets=[loss]) == []  # no unused-feed
        exe = static.Executor()
        feed_x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        (l0,) = exe.run(prog, feed={"x": feed_x,
                                    "y": np.array([0, 0], np.int64)},
                        fetch_list=[loss])
        (l1,) = exe.run(prog, feed={"x": feed_x,
                                    "y": np.array([2, 2], np.int64)},
                        fetch_list=[loss])
        assert not np.allclose(np.asarray(l0), np.asarray(l1))
