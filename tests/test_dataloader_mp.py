"""Multiprocess DataLoader over the native shm ring transport.

Mirrors the reference's dataloader tests
(fluid/tests/unittests/test_multiprocess_dataloader_*.py): order parity with
single-process iteration, iterable datasets with worker sharding, error
propagation from workers.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import _native
from paddle_tpu.io import DataLoader, Dataset, IterableDataset, get_worker_info

pytestmark = pytest.mark.skipif(not _native.AVAILABLE,
                                reason="native runtime not built")


class RangeDataset(Dataset):
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.full((3,), i, np.float32), np.int64(i)


class RangeIterable(IterableDataset):
    def __init__(self, n):
        self.n = n

    def __iter__(self):
        for i in range(self.n):
            yield np.full((2,), i, np.float32)


class FailingDataset(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.float32(i)


def _drain(loader):
    return [tuple(np.asarray(t.numpy()) for t in b) if isinstance(b, tuple)
            else np.asarray(b.numpy()) for b in loader]


def test_mp_matches_single_process_order():
    ds = RangeDataset(37)
    single = _drain(DataLoader(ds, batch_size=4, num_workers=0))
    multi = _drain(DataLoader(ds, batch_size=4, num_workers=3))
    assert len(single) == len(multi) == 10
    for s, m in zip(single, multi):
        np.testing.assert_array_equal(s[0], m[0])
        np.testing.assert_array_equal(s[1], m[1])


def test_mp_drop_last():
    ds = RangeDataset(10)
    multi = _drain(DataLoader(ds, batch_size=4, num_workers=2, drop_last=True))
    assert len(multi) == 2


def test_mp_iterable_dataset():
    ds = RangeIterable(20)
    single = _drain(DataLoader(ds, batch_size=5, num_workers=0))
    multi = _drain(DataLoader(ds, batch_size=5, num_workers=2))
    assert len(single) == len(multi) == 4
    for s, m in zip(single, multi):
        np.testing.assert_array_equal(s, m)


def test_mp_worker_error_propagates():
    loader = DataLoader(FailingDataset(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at 5"):
        _drain(loader)


def test_mp_worker_init_fn_and_info():
    seen = []

    class ProbeDataset(Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            info = get_worker_info()
            assert info is not None and info.num_workers == 2
            return np.int64(info.id)

    loader = DataLoader(ProbeDataset(), batch_size=1, num_workers=2)
    ids = [int(b.numpy()[0]) for b in loader]
    # batch b produced by worker b % 2
    assert ids == [0, 1, 0, 1]


def test_get_worker_info_none_in_parent():
    assert get_worker_info() is None
