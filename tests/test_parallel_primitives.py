"""Ring attention / Ulysses / SPMD pipeline on the 8-device CPU mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu.parallel import ring_attention, ulysses_attention, spmd_pipeline
from paddle_tpu.parallel.ring_attention import _full_attention

rng = np.random.RandomState(0)


def _ref_attention(q, k, v, causal):
    return np.asarray(_full_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    mesh = dist.make_mesh({"sp": 4})
    b, s, h, d = 2, 32, 4, 8  # s sharded 4-way -> 8 per device
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")

    fn = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp")))
    out = np.asarray(fn(q, k, v))
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_match():
    mesh = dist.make_mesh({"sp": 4})
    b, s, h, d = 1, 16, 2, 4
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")

    def ring_loss(q, k, v):
        out = jax.shard_map(
            lambda a, b_, c: ring_attention(a, b_, c, "sp", causal=True),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"))(q, k, v)
        return jnp.sum(out ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(_full_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(causal):
    mesh = dist.make_mesh({"sp": 4})
    b, s, h, d = 2, 32, 8, 4  # heads 8 divisible by sp=4
    q = rng.randn(b, s, h, d).astype("float32")
    k = rng.randn(b, s, h, d).astype("float32")
    v = rng.randn(b, s, h, d).astype("float32")

    fn = jax.jit(jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp")))
    out = np.asarray(fn(q, k, v))
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_spmd_pipeline_matches_sequential():
    mesh = dist.make_mesh({"pp": 4})
    n_stages, n_micro, mb, dim = 4, 8, 2, 16
    w = rng.randn(n_stages, dim, dim).astype("float32") * 0.1
    b = rng.randn(n_stages, dim).astype("float32") * 0.1
    x = rng.randn(n_micro, mb, dim).astype("float32")

    def stage_fn(params, h):
        wi, bi = params
        return jnp.tanh(h @ wi + bi)

    fn = jax.jit(jax.shard_map(
        lambda p, xx: spmd_pipeline(stage_fn, p, xx, "pp"),
        mesh=mesh, in_specs=((P("pp"), P("pp")), P(None)),
        out_specs=P(None)))
    out = np.asarray(fn((w, b), x))

    ref = x.copy()
    for s in range(n_stages):
        ref = np.tanh(ref @ w[s] + b[s])
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_spmd_pipeline_backward_trains():
    mesh = dist.make_mesh({"pp": 4})
    n_stages, n_micro, mb, dim = 4, 4, 2, 8
    w = (rng.randn(n_stages, dim, dim) * 0.3).astype("float32")
    x = rng.randn(n_micro, mb, dim).astype("float32")
    tgt = rng.randn(n_micro, mb, dim).astype("float32")

    def stage_fn(wi, h):
        return jnp.tanh(h @ wi)

    def loss_fn(w):
        out = jax.shard_map(
            lambda p, xx: spmd_pipeline(stage_fn, p, xx, "pp"),
            mesh=mesh, in_specs=(P("pp"), P(None)), out_specs=P(None))(w, x)
        return jnp.mean((out - tgt) ** 2)

    # gradient vs sequential reference
    def ref_loss(w):
        h = x
        for s in range(n_stages):
            h = jnp.tanh(h @ w[s])
        return jnp.mean((h - tgt) ** 2)

    g_pp = np.asarray(jax.grad(loss_fn)(w))
    g_ref = np.asarray(jax.grad(ref_loss)(w))
    np.testing.assert_allclose(g_pp, g_ref, rtol=1e-4, atol=1e-5)

    # and a few SGD steps reduce the loss inside one jit
    @jax.jit
    def train(w):
        for _ in range(5):
            l, g = jax.value_and_grad(loss_fn)(w)
            w = w - 0.5 * g
        return w, l

    w2, l_final = train(w)
    assert float(l_final) < float(ref_loss(w))
