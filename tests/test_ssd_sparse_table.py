"""Out-of-core (SSD) sparse table tests (reference:
`distributed/table/ssd_sparse_table.cc:362` — cold rows spill behind the
in-memory map and fault back transparently; snapshots and restart-resume
cover spilled rows)."""
import numpy as np

from paddle_tpu.distributed.ps import PsClient, PsServer, TableConfig
from paddle_tpu.distributed.ps.embedding import deterministic_init

DIM = 4


def _start(tmp_path, budget, optimizer="sgd", lr=0.1, table_id=1000):
    tables = [TableConfig(table_id, "sparse", DIM, optimizer, lr=lr,
                          init_range=0.1, seed=1000,
                          mem_budget_rows=budget,
                          spill_path=str(tmp_path / f"spill_{table_id}"))]
    srv = PsServer(tables, port=0)
    port = srv.start()
    cli = PsClient([f"127.0.0.1:{port}"])
    cli.register_sparse(table_id, DIM)
    return srv, cli


class TestSpillEvictRefault:
    def test_trains_past_ram_budget_and_refaults_exactly(self, tmp_path):
        """Push 60 keys through an 8-row budget: the table must evict to
        disk, keep answering pulls bit-exactly, and report honest
        in-mem/spilled counts."""
        srv, cli = _start(tmp_path, budget=8)
        try:
            keys = np.arange(60, dtype=np.uint64)
            g = np.ones((60, DIM), np.float32)
            cli.push_sparse_grad(1000, keys, g)       # sgd: init - 0.1
            in_mem, spilled, fails = cli.sparse_spill_info(1000)[0]
            assert in_mem <= 8
            assert spilled >= 52
            assert in_mem + spilled == 60
            assert cli.sparse_size(1000) == 60        # includes spilled
            mirror = deterministic_init(1000, keys, DIM, 0.1) - 0.1
            got = cli.pull_sparse(1000, keys)          # faults everything
            np.testing.assert_allclose(got, mirror, rtol=1e-5, atol=1e-7)
            # update a spilled-then-faulted row again: still exact
            cli.push_sparse_grad(1000, keys[:5], g[:5])
            got2 = cli.pull_sparse(1000, keys[:5])
            np.testing.assert_allclose(got2, mirror[:5] - 0.1,
                                       rtol=1e-5, atol=1e-7)
        finally:
            cli.stop_servers()
            srv.stop()

    def test_spilled_adam_state_survives_roundtrip(self, tmp_path):
        """Adam m/v/t ride the spill record: a budget-1 table must stay
        bit-identical to an unbounded one under the same grad stream."""
        srv, cli = _start(tmp_path, budget=4, optimizer="adam", lr=0.05)
        try:
            keys = np.arange(20, dtype=np.uint64)
            rng = np.random.RandomState(0)
            grads = [rng.randn(20, DIM).astype(np.float32)
                     for _ in range(4)]
            for gstep in grads:
                cli.push_sparse_grad(1000, keys, gstep)
            spilled_vals = cli.pull_sparse(1000, keys)
            in_mem, spilled, fails = cli.sparse_spill_info(1000)[0]
            assert spilled > 0
        finally:
            cli.stop_servers()
            srv.stop()
        # ground truth from a fresh unbounded server, same pushes
        srv2 = PsServer(
            [TableConfig(1000, "sparse", DIM, "adam", lr=0.05,
                         init_range=0.1, seed=1000)], port=0)
        port2 = srv2.start()
        cli2 = PsClient([f"127.0.0.1:{port2}"])
        cli2.register_sparse(1000, DIM)
        try:
            for gstep in grads:
                cli2.push_sparse_grad(1000, keys, gstep)
            want = cli2.pull_sparse(1000, keys)
            np.testing.assert_array_equal(spilled_vals, want)
        finally:
            cli2.stop_servers()
            srv2.stop()


class TestSpillSnapshotRestart:
    def test_snapshot_restart_resume_includes_spilled_rows(self, tmp_path):
        """The restart-resume contract of test_parameter_server
        (bit-exact optimizer state across save/stop/load) must hold when
        most rows live on disk."""
        snap = str(tmp_path / "ssd_snap")
        keys = np.arange(40, dtype=np.uint64)
        rng = np.random.RandomState(2)
        srv, cli = _start(tmp_path, budget=6, optimizer="adam", lr=0.05)
        try:
            for _ in range(3):
                cli.push_sparse_grad(1000, keys,
                                     rng.rand(40, DIM).astype(np.float32))
            cli.save(snap)
            mid = cli.pull_sparse(1000, keys)
            g_next = rng.rand(40, DIM).astype(np.float32)
            cli.push_sparse_grad(1000, keys, g_next)
            want = cli.pull_sparse(1000, keys)
        finally:
            cli.stop_servers()
            srv.stop()
        # fresh process-state server (new spill file), budget still 6:
        # load must restore all 40 rows (re-spilling past the budget),
        # and the SAME next push must give the SAME result (m/v/t intact)
        (tmp_path / "b").mkdir(exist_ok=True)
        srv2, cli2 = _start(tmp_path / "b", budget=6, optimizer="adam",
                            lr=0.05)
        try:
            cli2.load(snap)
            in_mem, spilled, fails = cli2.sparse_spill_info(1000)[0]
            assert in_mem <= 6 and in_mem + spilled == 40
            np.testing.assert_array_equal(cli2.pull_sparse(1000, keys),
                                          mid)
            cli2.push_sparse_grad(1000, keys, g_next)
            np.testing.assert_array_equal(cli2.pull_sparse(1000, keys),
                                          want)
        finally:
            cli2.stop_servers()
            srv2.stop()
