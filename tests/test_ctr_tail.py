"""CTR/serving op tail (reference: contrib/layers/nn.py shuffle_batch,
filter_by_instag, search_pyramid_hash, rank_attention, tree_conv,
var_conv_2d + their C++ kernels)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import ops

rng = np.random.RandomState(9)


def test_shuffle_batch_is_permutation():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(6, 2))
    paddle.seed(3)
    out = ops.shuffle_batch(x)
    got = out.numpy()
    assert sorted(got[:, 0].tolist()) == list(range(0, 12, 2))
    # seeded: deterministic
    a = ops.shuffle_batch(x, seed=5).numpy()
    b = ops.shuffle_batch(x, seed=5).numpy()
    np.testing.assert_array_equal(a, b)


def test_filter_by_instag():
    ins = paddle.to_tensor(rng.rand(4, 3).astype(np.float32))
    tags = [[1, 2], [3], [2, 7], [4]]
    out, lw, idx = ops.filter_by_instag(ins, tags,
                                        paddle.to_tensor(
                                            np.array([2, 4], np.int64)))
    np.testing.assert_allclose(out.numpy(), ins.numpy()[[0, 2, 3]])
    assert lw.numpy().shape == (3, 1)
    np.testing.assert_array_equal(idx.numpy()[:, 1], [0, 2, 3])
    # empty result: one padded row, zero loss weight
    out2, lw2, _ = ops.filter_by_instag(ins, tags,
                                        paddle.to_tensor(
                                            np.array([99], np.int64)))
    assert out2.numpy().shape == (1, 3)
    assert float(lw2.numpy().sum()) == 0.0


def test_pyramid_hash_shapes_and_grads():
    W = paddle.to_tensor(rng.rand(64, 4).astype(np.float32))
    W.stop_gradient = False
    ids = paddle.to_tensor(
        np.array([[3, 7, 9, 0], [5, 2, 0, 0]], np.int32))
    out = ops.search_pyramid_hash(ids, W, num_emb=8, space_len=64,
                                  pyramid_layer=3, rand_len=4)
    assert out.shape == [2, 8]
    out.sum().backward()
    assert W.grad is not None and float(abs(W.grad.numpy()).sum()) > 0
    # same ids -> same embedding (deterministic hash)
    out2 = ops.search_pyramid_hash(ids, W, num_emb=8, space_len=64,
                                   pyramid_layer=3, rand_len=4)
    np.testing.assert_allclose(out.numpy(), out2.numpy())


def test_rank_attention_matches_manual():
    N, d, K, out_col = 3, 2, 2, 3
    x = rng.rand(N, d).astype(np.float32)
    p = rng.rand(d * K * K, out_col).astype(np.float32)
    # ins 0: own rank 1, one related (rank 2, row 1); ins 1: own rank 2,
    # related (rank 1, row 0) and (rank 2, row 1); ins 2: invalid (rank 0)
    ro = np.array([[1, 2, 1, 0, 0],
                   [2, 1, 0, 2, 1],
                   [0, 0, 0, 0, 0]], np.int32)
    out = ops.rank_attention(paddle.to_tensor(x), paddle.to_tensor(ro),
                             paddle.to_tensor(p), max_rank=K).numpy()
    pb = p.reshape(K * K, d, out_col)
    want0 = x[1] @ pb[(1 - 1) * K + (2 - 1)]
    want1 = x[0] @ pb[(2 - 1) * K + (1 - 1)] + x[1] @ pb[(2 - 1) * K + (2 - 1)]
    np.testing.assert_allclose(out[0], want0, rtol=1e-5)
    np.testing.assert_allclose(out[1], want1, rtol=1e-5)
    np.testing.assert_allclose(out[2], np.zeros(out_col), atol=1e-7)


def test_tree_conv_root_leaf():
    """2-node tree (1 -> 2), max_depth 2: root patch = {self, child},
    leaf patch = {self}; eta coefficients per tree2col.cc."""
    B, N, C, O, F_ = 1, 2, 2, 3, 1
    nodes = rng.rand(B, N, C).astype(np.float32)
    edges = np.zeros((B, 3, 2), np.int32)
    edges[0, 0] = [1, 2]
    w = rng.rand(C, 3, O, F_).astype(np.float32)
    out = ops.tree_conv(paddle.to_tensor(nodes), paddle.to_tensor(edges),
                        paddle.to_tensor(w), max_depth=2).numpy()
    et0, el0, er0 = 1.0, 0.0, 0.0  # depth 0: eta_t=(2-0)/2=1
    etc, elc, erc = 0.5, 0.25, 0.25  # child: depth1, index1, pclen1
    want_root = np.einsum("c,ceo->o",
                          nodes[0, 0], w[:, :, :, 0] * np.array(
                              [et0, el0, er0])[None, :, None]) + \
        np.einsum("c,ceo->o", nodes[0, 1], w[:, :, :, 0] * np.array(
            [etc, elc, erc])[None, :, None])
    np.testing.assert_allclose(out[0, 0, :, 0], want_root, rtol=1e-4)
    want_leaf = np.einsum("c,ceo->o", nodes[0, 1],
                          w[:, :, :, 0] * np.array(
                              [et0, el0, er0])[None, :, None])
    np.testing.assert_allclose(out[0, 1, :, 0], want_leaf, rtol=1e-4)


def test_var_conv_2d_masks_padding():
    B, H, W = 2, 6, 6
    x = np.ones((B, 1, H, W), np.float32)
    f = np.ones((1, 1, 3, 3), np.float32)
    out = ops.var_conv_2d(paddle.to_tensor(x),
                          paddle.to_tensor(np.array([4, 6], np.int32)),
                          paddle.to_tensor(np.array([4, 6], np.int32)),
                          paddle.to_tensor(f)).numpy()
    # outputs beyond each sample's valid extent are exactly zero
    assert np.all(out[0, 0, 4:, :] == 0) and np.all(out[0, 0, :, 4:] == 0)
    assert out[0, 0, 1, 1] == 9.0  # interior of the valid region
    assert np.all(out[1, 0] != 0)


def test_bilateral_slice_constant_grid():
    """A grid holding the same affine transform in every cell must reduce
    to that exact per-pixel affine map (reference kernel semantics)."""
    N, Cin, Cout, H, W = 1, 2, 2, 4, 4
    gd, gh, gw = 3, 2, 2
    A = rng.rand(Cout, Cin).astype(np.float32)
    b = rng.rand(Cout).astype(np.float32)
    stride = Cin + 1
    grid = np.zeros((N, Cout * stride, gd, gh, gw), np.float32)
    for o in range(Cout):
        for i in range(Cin):
            grid[0, o * stride + i] = A[o, i]
        grid[0, o * stride + Cin] = b[o]
    x = rng.rand(N, Cin, H, W).astype(np.float32)
    guide = rng.rand(N, H, W).astype(np.float32)
    out = ops.bilateral_slice(paddle.to_tensor(x), paddle.to_tensor(guide),
                              paddle.to_tensor(grid), has_offset=True)
    want = np.einsum("oi,nihw->nohw", A, x) + b[None, :, None, None]
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-4, atol=1e-5)


def test_bilateral_slice_grads_flow():
    N, Cin, H, W = 1, 1, 3, 3
    gd, gh, gw = 2, 2, 2
    grid = paddle.to_tensor(rng.rand(N, 2, gd, gh, gw).astype(np.float32))
    grid.stop_gradient = False
    x = paddle.to_tensor(rng.rand(N, Cin, H, W).astype(np.float32))
    guide = paddle.to_tensor(rng.rand(N, H, W).astype(np.float32))
    out = ops.bilateral_slice(x, guide, grid, has_offset=True)
    out.sum().backward()
    assert grid.grad is not None
    assert float(abs(grid.grad.numpy()).sum()) > 0
