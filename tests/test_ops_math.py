"""Op correctness vs numpy (reference test model: unittests/test_*_op.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops

from op_test import check_grad, check_output

rng = np.random.RandomState(7)


@pytest.mark.parametrize("name,np_fn", [
    ("exp", np.exp), ("log", None), ("sqrt", None), ("tanh", np.tanh),
    ("sin", np.sin), ("cos", np.cos), ("abs", np.abs), ("square", np.square),
    ("floor", np.floor), ("ceil", np.ceil), ("sign", np.sign),
])
def test_unary(name, np_fn):
    x = rng.rand(3, 4).astype("float32") + 0.5
    np_fn = np_fn or getattr(np, name)
    check_output(getattr(ops, name), np_fn, [x])


@pytest.mark.parametrize("name,np_fn", [
    ("add", np.add), ("subtract", np.subtract), ("multiply", np.multiply),
    ("divide", np.divide), ("maximum", np.maximum), ("minimum", np.minimum),
])
def test_binary(name, np_fn):
    x = rng.rand(3, 4).astype("float32") + 1.0
    y = rng.rand(3, 4).astype("float32") + 1.0
    check_output(getattr(ops, name), np_fn, [x, y])


def test_binary_broadcast():
    x = rng.rand(3, 4).astype("float32")
    y = rng.rand(4).astype("float32")
    check_output(ops.add, np.add, [x, y])
    check_output(ops.multiply, np.multiply, [x, y])


@pytest.mark.parametrize("axis,keepdim", [(None, False), (0, False),
                                          (1, True), ((0, 1), False)])
def test_reductions(axis, keepdim):
    x = rng.rand(3, 4, 5).astype("float32")
    check_output(lambda t: ops.sum(t, axis=axis, keepdim=keepdim),
                 lambda a: np.sum(a, axis=axis, keepdims=keepdim), [x])
    check_output(lambda t: ops.mean(t, axis=axis, keepdim=keepdim),
                 lambda a: np.mean(a, axis=axis, keepdims=keepdim), [x])
    check_output(lambda t: ops.max(t, axis=axis, keepdim=keepdim),
                 lambda a: np.max(a, axis=axis, keepdims=keepdim), [x])


def test_matmul():
    x = rng.rand(4, 5).astype("float32")
    y = rng.rand(5, 3).astype("float32")
    check_output(ops.matmul, np.matmul, [x, y])
    # batched
    xb = rng.rand(2, 4, 5).astype("float32")
    yb = rng.rand(2, 5, 3).astype("float32")
    check_output(ops.matmul, np.matmul, [xb, yb])
    # transpose flags
    check_output(lambda a, b: ops.matmul(a, b, transpose_y=True),
                 lambda a, b: a @ b.T, [x, rng.rand(3, 5).astype("float32")])


def test_matmul_grad():
    x = rng.rand(3, 4).astype("float32")
    y = rng.rand(4, 2).astype("float32")
    check_grad(ops.matmul, [x, y], grad_index=0)
    check_grad(ops.matmul, [x, y], grad_index=1)


def test_unary_grads():
    x = rng.rand(3, 3).astype("float32") + 0.5
    for fn in (ops.exp, ops.log, ops.sqrt, ops.tanh, ops.square):
        check_grad(fn, [x])


def test_manipulation():
    x = rng.rand(2, 3, 4).astype("float32")
    check_output(lambda t: ops.reshape(t, [6, 4]),
                 lambda a: a.reshape(6, 4), [x])
    check_output(lambda t: ops.transpose(t, [2, 0, 1]),
                 lambda a: a.transpose(2, 0, 1), [x])
    check_output(lambda t: ops.squeeze(ops.unsqueeze(t, 0), 0),
                 lambda a: a, [x])
    check_output(lambda t: ops.flatten(t, 1),
                 lambda a: a.reshape(2, 12), [x])
    check_output(lambda t: ops.flip(t, [1]),
                 lambda a: a[:, ::-1], [x])


def test_concat_split_stack():
    a = rng.rand(2, 3).astype("float32")
    b = rng.rand(2, 3).astype("float32")
    out = ops.concat([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
    np.testing.assert_allclose(out.numpy(), np.concatenate([a, b], 0))
    parts = ops.split(paddle.to_tensor(a), 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1]
    parts = ops.split(paddle.to_tensor(a), [1, -1], axis=1)
    assert parts[1].shape == [2, 2]
    st = ops.stack([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
    assert st.shape == [2, 2, 3]


def test_concat_grad():
    a = rng.rand(2, 2).astype("float32")
    b = rng.rand(2, 2).astype("float32")
    check_grad(lambda x, y: ops.concat([x, y], axis=1), [a, b], grad_index=0)


def test_gather_indexing():
    x = rng.rand(5, 4).astype("float32")
    idx = np.array([0, 2, 4])
    out = ops.gather(paddle.to_tensor(x), paddle.to_tensor(idx))
    np.testing.assert_allclose(out.numpy(), x[idx])
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(t[1:3].numpy(), x[1:3])
    np.testing.assert_allclose(t[:, 2].numpy(), x[:, 2])
    np.testing.assert_allclose(t[paddle.to_tensor(idx)].numpy(), x[idx])


def test_getitem_grad():
    x = rng.rand(4, 4).astype("float32")
    check_grad(lambda t: t[1:3, :2], [x])


def test_topk_argmax():
    x = rng.rand(3, 6).astype("float32")
    vals, idx = ops.topk(paddle.to_tensor(x), 2)
    ref = np.sort(x, axis=-1)[:, ::-1][:, :2]
    np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)
    am = ops.argmax(paddle.to_tensor(x), axis=1)
    np.testing.assert_array_equal(am.numpy(), x.argmax(1))


def test_cumsum_sort():
    x = rng.rand(3, 4).astype("float32")
    check_output(lambda t: ops.cumsum(t, axis=1),
                 lambda a: np.cumsum(a, axis=1), [x])
    check_output(lambda t: ops.sort(t, axis=1),
                 lambda a: np.sort(a, axis=1), [x])


def test_where_clip():
    x = rng.randn(3, 4).astype("float32")
    y = rng.randn(3, 4).astype("float32")
    cond = x > 0
    out = ops.where(paddle.to_tensor(cond), paddle.to_tensor(x),
                    paddle.to_tensor(y))
    np.testing.assert_allclose(out.numpy(), np.where(cond, x, y))
    check_output(lambda t: ops.clip(t, -0.5, 0.5),
                 lambda a: np.clip(a, -0.5, 0.5), [x])


def test_scalar_arith_dunders():
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    np.testing.assert_allclose((x + 1).numpy(), [2, 3])
    np.testing.assert_allclose((2 * x).numpy(), [2, 4])
    np.testing.assert_allclose((x / 2).numpy(), [0.5, 1])
    np.testing.assert_allclose((x ** 2).numpy(), [1, 4])
    np.testing.assert_allclose((-x).numpy(), [-1, -2])
    np.testing.assert_allclose((1 - x).numpy(), [0, -1])


def test_einsum():
    a = rng.rand(2, 3).astype("float32")
    b = rng.rand(3, 4).astype("float32")
    out = ops.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


def test_cast_dtypes():
    x = paddle.to_tensor(np.array([1.5, 2.5], np.float32))
    assert ops.cast(x, "int32").dtype == np.int32
    assert ops.cast(x, "bfloat16").dtype.name == "bfloat16"
    assert x.astype("float16").dtype == np.float16
