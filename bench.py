"""Flagship benchmark: BERT-base MLM pretraining step, bf16, whole-program XLA.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no numbers (BASELINE.md); the north-star target is
50% MFU for BERT-base pretraining — vs_baseline reports measured_MFU / 0.50.

Program structure (each measured on v5e, kept because it won):
- ONE compiled program per k training steps (k-unroll amortizes the
  per-execute dispatch/tunnel overhead, ~5 ms/step on the axon tunnel).
  k=20 beat k=16 by ~2.2% in the round-4 back-to-back A/B (k=32 compiles
  >10 min; don't).
- PURE-bf16 parameters with fp32 master weights in AdamW
  (multi_precision): halves the param-read HBM traffic the O1 auto_cast
  paid per use; +0.5% back-to-back, composes with k=20 (0.511→0.525 MFU
  in the round-4 A/B, benchmarks/ab_mfu.py k16 vs k20_bf16).
- jax.lax.optimization_barrier between the backward and the AdamW update:
  without it XLA interleaves the update fusions with the backward matmuls
  and their HBM throughput drops ~3x (the round-2 fix was a separate
  program; the barrier gets the same effect without the program boundary).
- Timing takes the best of N windows (6 on TPU): the chip is shared, and a
  transient co-tenant burst in one window would otherwise report as a
  regression.
- `--scan` switches the program structure from the python-unrolled k-step
  body to the scan-compiled step program (`to_static(one_step,
  scan_steps=k)`, stacked [k, ...] batch as scan xs): same math, compile
  time ~independent of k — use it with `--k 32`/`--k 64`, where the
  unrolled trace/compile is prohibitive (>10 min). Steady-state MFU of
  both structures is compared back-to-back in benchmarks/ab_mfu.py.
"""
import argparse
import json
import sys
import time

import numpy as np

PEAK_BF16_FLOPS = {
    "tpu": 197e12,   # TPU v5e per-chip bf16 peak
    "cpu": 1e11,     # nominal, for local smoke runs only
}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scan", action="store_true",
                    help="scan-compiled step program instead of the "
                         "python-unrolled k-step body")
    ap.add_argument("--k", type=int, default=None,
                    help="dispatch-amortization factor (steps per "
                         "compiled program); default 20 TPU / 2 CPU")
    ap.add_argument("--zero", type=int, default=0, choices=(0, 1, 2, 3),
                    help="ZeRO stage: shard optimizer state (moments + "
                         "fp32 masters) 1/dp per chip, bucketed "
                         "psum_scatter grad reduction + param all_gather "
                         "inside the scan step (implies --scan; dp = all "
                         "local devices). Stage 3 also shards the "
                         "PARAMETERS 1/dp: per-bucket all_gather "
                         "materializes them just-in-time before forward "
                         "and the update writes only shard rows")
    ap.add_argument("--prefetch", default="on", choices=("on", "off"),
                    help="latency-hiding ZeRO step (default on): "
                         "double-buffered bucket pipeline — next "
                         "bucket's param all_gather is emitted under "
                         "the current bucket's compute, grad "
                         "reduce-scatter under the next bucket's "
                         "update, and the step tail re-gathers bucket "
                         "0 into a carry slot so the next step starts "
                         "warm. 'off' keeps the on-demand serial "
                         "schedule (the A/B control; bitwise-equal "
                         "losses either way)")
    ap.add_argument("--accumulate", type=int, default=1,
                    help="gradient-accumulation window: group the k "
                         "inner steps into k/N windows, optimizer "
                         "update + reduce/all_gather once per window "
                         "(cuts collective bytes per step ~N x for "
                         "zero<=1; needs k %% N == 0)")
    ap.add_argument("--remat", default="none",
                    choices=("none", "full", "selective", "offload"),
                    help="activation-recompute policy applied per "
                         "encoder layer (paddle_tpu.recompute): trade "
                         "recompute FLOPs (full), saved matmul outputs "
                         "(selective), or host traffic (offload — falls "
                         "back loudly to selective without a "
                         "pinned_host memory space) for the HBM the "
                         "backward otherwise holds — then spend it on "
                         "--batch/--k")
    ap.add_argument("--batch", type=int, default=None,
                    help="override the per-step batch size (the knob "
                         "the remat-freed HBM buys back)")
    args_cli = ap.parse_args(argv)
    if args_cli.zero:
        args_cli.scan = True  # ZeRO is an option of the scan step program
    if args_cli.accumulate > 1:
        args_cli.scan = True  # accumulation windows live in the scan step

    import jax
    import jax.lax as lax

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    import paddle_tpu as paddle
    from paddle_tpu.models import BertConfig, BertForPretraining, synthetic_mlm_batch

    paddle.seed(0)
    if on_tpu:
        cfg = BertConfig(vocab_size=30720, hidden_dropout=0.0,
                         attention_dropout=0.0)  # base, vocab padded to 128x
        batch, seq, k, iters, warmup, windows = 16, 512, 20, 1, 1, 6
    else:
        cfg = BertConfig(vocab_size=2048, hidden_size=128, num_layers=2,
                         num_heads=4, intermediate_size=512,
                         hidden_dropout=0.0, attention_dropout=0.0)
        batch, seq, k, iters, warmup, windows = 4, 128, 2, 2, 1, 1
    if args_cli.k:
        k = args_cli.k
    if args_cli.batch is not None:
        if args_cli.batch < 1:
            raise SystemExit(f"--batch must be >= 1, got {args_cli.batch}")
        batch = args_cli.batch

    dp = 1
    if args_cli.zero:
        from paddle_tpu.distributed import parallel_env
        dp = jax.device_count()
        parallel_env.set_mesh(parallel_env.make_mesh({"dp": dp}))
        if batch % dp:
            batch = max(dp, batch - batch % dp)

    model = BertForPretraining(cfg)
    if args_cli.remat != "none":
        # per-encoder-layer remat segments (the granularity that pays:
        # layer boundaries are the only fwd->bwd residuals left; each
        # layer's attention/FFN internals rematerialize in backward)
        for layer in model.bert.layers:
            layer.enable_recompute(args_cli.remat)
    if on_tpu:
        model.to("bfloat16")  # pure-bf16 params, fp32 masters in AdamW
    opt = paddle.optimizer.AdamW(parameters=model.parameters(),
                                 learning_rate=1e-4,
                                 multi_precision=on_tpu)
    if args_cli.zero:
        n_sharded = opt._zero_enable(axis="dp", stage=args_cli.zero,
                                     prefetch=args_cli.prefetch == "on")
        print(f"# zero{args_cli.zero}: dp={dp} sharded_stores={n_sharded} "
              f"state_bytes/chip={opt._zero_state_bytes()} "
              f"prefetch={args_cli.prefetch}",
              file=sys.stderr)
    params = list(model.parameters())

    def one_step(ids, tok, labels, nsp_labels):
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
            logits, nsp = model(ids, tok)
            loss = model.loss(logits, nsp, labels, nsp_labels)
        loss.backward()
        withg = [p for p in params if p._grad is not None]
        barred = lax.optimization_barrier(tuple(p._grad for p in withg))
        for p, v in zip(withg, barred):
            p._grad = v
        opt.step()
        opt.clear_grad()
        return loss

    if args_cli.scan:
        # scan-compiled program: one traced body rolled k times; the
        # [k, ...]-stacked batch is the scan xs (same microbatch repeated
        # here, matching the unrolled control's batch reuse). Under
        # --zero the scan runs inside shard_map over 'dp' and the AdamW
        # update is the sharded bucketed-psum_scatter step. --accumulate
        # groups the k steps into windows with one update each.
        if args_cli.accumulate > 1 and k % args_cli.accumulate:
            raise SystemExit(f"--k {k} must be a multiple of "
                             f"--accumulate {args_cli.accumulate}")
        step = paddle.jit.to_static(
            one_step, scan_steps=k,
            dp_axis="dp" if args_cli.zero else None,
            accumulate_steps=(args_cli.accumulate
                              if args_cli.accumulate > 1 else None))
    else:
        def k_steps(ids, tok, labels, nsp_labels):
            for _ in range(k):
                loss = one_step(ids, tok, labels, nsp_labels)
            return loss

        step = paddle.jit.to_static(k_steps)

    # window telemetry cross-check: the per-model FLOP count (not the
    # 6*N*T estimate) drives the exported MFU gauge
    from paddle_tpu.observability.step import StepTimer
    timer = StepTimer(window=max(windows * iters, 2),
                      flops_per_token=model.flops_per_token(seq),
                      peak_flops=PEAK_BF16_FLOPS["tpu" if on_tpu else "cpu"],
                      publish_as="bench")

    def run(bs):
        ids, tok, labels, nsp = synthetic_mlm_batch(bs, seq,
                                                    vocab_size=cfg.vocab_size)
        if args_cli.scan:
            stack = lambda a: np.broadcast_to(a, (k,) + a.shape).copy()
            ids, tok, labels, nsp = (stack(a) for a in
                                     (ids, tok, labels, nsp))
        t_ids = paddle.to_tensor(ids)
        t_tok = paddle.to_tensor(tok)
        t_lab = paddle.to_tensor(labels)
        t_nsp = paddle.to_tensor(nsp)
        args = (t_ids, t_tok, t_lab, t_nsp)
        t_compile = time.perf_counter()
        for _ in range(warmup):
            loss = step(*args)
        last = (lambda l: l[-1]) if args_cli.scan else (lambda l: l)
        float(last(loss).numpy())  # hard sync (device->host) before timing
        t_compile = time.perf_counter() - t_compile
        print(f"# first-call (trace+compile+run) {t_compile:.1f}s "
              f"structure={'scan' if args_cli.scan else 'unroll'} k={k}",
              file=sys.stderr)
        best = 0.0
        timer.start()
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(iters):
                loss = step(*args)
            loss_host = float(last(loss).numpy())  # true sync: chains steps
            dt = time.perf_counter() - t0
            timer.step(tokens=bs * seq * iters * k)
            best = max(best, bs * seq * iters * k / dt)
        return best, loss_host

    tokens_per_s = None
    for bs in (batch, batch // 2, max(batch // 4, 1)):
        try:
            tokens_per_s, loss_val = run(bs)
            batch = bs
            break
        except Exception as e:  # OOM fallback
            if "RESOURCE_EXHAUSTED" in str(e) or "out of memory" in str(e).lower():
                continue
            raise
    if tokens_per_s is None:
        print(json.dumps({"metric": "bert_base_pretrain_tokens_per_s_per_chip",
                          "value": 0.0, "unit": "tokens/s",
                          "backend": backend, "vs_baseline": 0.0}))
        return

    flops_per_token = model.flops_per_token(seq)
    peak = PEAK_BF16_FLOPS["tpu" if on_tpu else "cpu"]
    mfu = tokens_per_s * flops_per_token / peak
    result = {
        "metric": "bert_base_pretrain_tokens_per_s_per_chip",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/s",
        "backend": backend,
        "vs_baseline": round(mfu / 0.50, 4),
    }
    print(json.dumps(result))
    t = timer.telemetry()
    print(f"# backend={backend} batch={batch} seq={seq} k={k} "
          f"structure={'scan' if args_cli.scan else 'unroll'} "
          f"zero={args_cli.zero} accumulate={args_cli.accumulate} "
          f"remat={args_cli.remat} "
          f"mfu={mfu:.3f} timer_mfu={t.get('mfu', 0.0):.3f} "
          f"loss={loss_val:.3f}", file=sys.stderr)
    if args_cli.remat != "none":
        # memory side of the trade: XLA attribution (meaningful on TPU,
        # where barriers survive) + the backend-independent jaxpr
        # liveness peak (the meter that shows remat even on CPU) — run
        # `--remat none` back to back for the A/B
        try:
            xs = next(iter(step.memory_stats().values()))
            ts = next(iter(step.traced_memory_stats().values()))
            print(f"# remat memory: xla_temp={xs['temp_bytes']} "
                  f"xla_peak={xs['peak_bytes']} "
                  f"host_offload={xs.get('host_offload_bytes', 0)} "
                  f"jaxpr_peak={ts['peak_bytes']}", file=sys.stderr)
        except Exception as e:
            print(f"# remat memory stats unavailable: {e}",
                  file=sys.stderr)
    if args_cli.zero or args_cli.accumulate > 1:
        # after the timed windows (the AOT stats path recompiles once):
        # the psum_scatter-vs-psum evidence for this structure, plus the
        # per-execution view (trip-count-weighted) that shows the
        # accumulation window dividing reduction traffic
        try:
            stats = step.export_collective_bytes()
            top = ", ".join(f"{s['op']}[{s['axis']}] {s['bytes']}B"
                            f"x{s['count']}" for s in stats[:4])
            print(f"# in-trace collectives: {top}", file=sys.stderr)
            per_exec = step.collective_stats(per_execution=True)
            top = ", ".join(f"{s['op']}[{s['axis']}] {s['bytes']}B"
                            f"x{s['count']}" for s in per_exec[:4])
            print(f"# per-execution collectives: {top}", file=sys.stderr)
        except Exception as e:  # stats are evidence, never a bench failure
            print(f"# in-trace collectives unavailable: {e}",
                  file=sys.stderr)
    if args_cli.zero:
        # the --prefetch A/B's structural evidence: emission-order
        # overlap headroom from the traced jaxpr (backend-independent —
        # the number the mlp_zero3_schedulable_overlap row gates)
        try:
            sched = step.schedulable_stats()
            print(f"# schedulable overlap: "
                  f"{sched['schedulable_overlap']:.4f} "
                  f"(prefetch={args_cli.prefetch}, "
                  f"source={sched['source']})", file=sys.stderr)
        except Exception as e:
            print(f"# schedulable overlap unavailable: {e}",
                  file=sys.stderr)


if __name__ == "__main__":
    main()
