"""API compatibility gate (reference: `tools/check_api_compatible.py` —
CI fails when the public API surface drifts from the frozen API.spec
without the spec being updated in the same change).

Usage: python tools/check_api_compatible.py
Exit 0 = surface matches API.spec; exit 1 = drift (removed or changed
entries are breaking; additions are listed but allowed — refresh the spec
with `python tools/print_signatures.py --write`).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from print_signatures import SPEC_PATH, collect  # noqa: E402


def main():
    if not os.path.exists(SPEC_PATH):
        print("API.spec missing — generate it with "
              "`python tools/print_signatures.py --write`")
        return 1
    with open(SPEC_PATH) as f:
        frozen = set(line.rstrip("\n") for line in f if line.strip())
    current = set(collect())

    def key(line):
        return line.split(" ", 1)[0]

    frozen_by_key = {key(ln): ln for ln in frozen}
    current_by_key = {key(ln): ln for ln in current}

    removed = sorted(set(frozen_by_key) - set(current_by_key))
    added = sorted(set(current_by_key) - set(frozen_by_key))
    changed = sorted(k for k in set(frozen_by_key) & set(current_by_key)
                     if frozen_by_key[k] != current_by_key[k])

    for k in removed:
        print(f"REMOVED  {frozen_by_key[k]}")
    for k in changed:
        print(f"CHANGED  {frozen_by_key[k]}")
        print(f"     ->  {current_by_key[k]}")
    for k in added:
        print(f"added    {current_by_key[k]}")

    if removed or changed:
        print(f"\nAPI drift: {len(removed)} removed, {len(changed)} "
              f"changed (breaking). If intentional, refresh the spec: "
              f"python tools/print_signatures.py --write")
        return 1
    print(f"API surface compatible ({len(current)} entries, "
          f"{len(added)} new).")
    return 0


if __name__ == "__main__":
    sys.exit(main())
