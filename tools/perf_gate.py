"""CI perf-regression gate (observability/gate.py front-end).

Compare a benchmark results file — or a fresh `benchmarks/run_all.py`
run — against a pinned baseline; exit non-zero on regression so CI can
block the merge. Evidence-first: record runs with `--out`, pin them with
`--write-baseline`, and the A/B trail lives in version control next to
the code it measures.

Usage:
    # gate a recorded results file (fast; no benches run) against the
    # pinned repo baseline (--baseline defaults to BASELINE_PERF.json;
    # TPU-pinned values are compared on a TPU host, PRESENCE-checked on
    # a CPU smoke host — see observability/gate.py):
    python tools/perf_gate.py --current results.json

    # run the ladder and gate in one go:
    python tools/perf_gate.py --baseline BASELINE_PERF.json \
        --configs resnet,allreduce

    # pin the current run as the new baseline:
    python tools/perf_gate.py --configs resnet,allreduce \
        --write-baseline BASELINE_PERF.json

Exit codes: 0 pass, 1 usage/bench error, 2 regression.
"""
import argparse
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.observability import gate  # noqa: E402


def _run_benches(configs):
    spec = importlib.util.spec_from_file_location(
        "pt_bench_run_all", os.path.join(REPO, "benchmarks", "run_all.py"))
    run_all = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(run_all)
    results, _failed = run_all.run_benches(configs)
    return results


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="perf-regression gate over benchmarks/run_all.py "
                    "result records")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO, "BASELINE_PERF.json"),
                    help="pinned baseline JSON (default: the repo's "
                    "BASELINE_PERF.json)")
    ap.add_argument("--current", help="results JSON to gate "
                    "(default: run --configs)")
    ap.add_argument("--configs", default="resnet,allreduce",
                    help="benches to run when --current is not given")
    ap.add_argument("--tolerance", type=float,
                    default=gate.DEFAULT_TOLERANCE)
    ap.add_argument("--write-baseline", dest="write_baseline",
                    help="store the current results as a baseline and exit")
    args = ap.parse_args(argv)

    if args.current:
        results = list(gate.load_results(args.current).values())
    else:
        results = _run_benches(args.configs)

    if args.write_baseline:
        n = gate.write_baseline(results, args.write_baseline)
        print(f"wrote {n} baseline metrics to {args.write_baseline}")
        return 0

    if not args.baseline:
        ap.error("--baseline is required unless --write-baseline is given")
    ok, report = gate.compare(
        gate.load_results(args.baseline),
        {r["metric"]: r for r in results if "metric" in r},
        tolerance=args.tolerance)
    print(gate.format_report(report))
    print("PERF GATE:", "PASS" if ok else "FAIL")
    return 0 if ok else 2


if __name__ == "__main__":
    sys.exit(main())
