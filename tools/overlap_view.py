#!/usr/bin/env python
"""Collective overlap viewer: text-Gantt schedule timelines + the flag
A/B diff over ``observability.overlap``.

Renders per-program hidden/exposed collective time from the compiled
schedule — each collective a bar (``#`` hidden behind scheduled
compute, ``=`` exposed), in schedule order per computation — plus the
summary gauges (``collective_overlap_efficiency``, exposed fraction,
async-pair vs sync counts). With a ``jax.profiler`` trace directory it
correlates the schedule ESTIMATE against measured collective span
wall-times from the trace.

Sources (pick one):

    # attribute the benchmark ladder's verified program twins
    python tools/overlap_view.py --ladder [--configs zero3,allreduce]

    # analyze a compiled HLO dump (e.g. StaticFunction.hlo_text())
    python tools/overlap_view.py --hlo step.hlo

    # flag A/B: efficiency / schedulable-overlap / exposed-time deltas
    # between two captures (the latency-hiding on-vs-off evidence view;
    # d_sched moves even on sync-schedule backends where d_eff cannot)
    python tools/overlap_view.py --diff off.json on.json

    # record a capture for a later --diff
    python tools/overlap_view.py --ladder --out off.json

    # correlate against measured spans from jax.profiler.trace(dir)
    python tools/overlap_view.py --hlo step.hlo --trace /tmp/prof

Exit codes: 0 ok, 1 usage/attribution error.
"""
import argparse
import glob
import gzip
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BAR_WIDTH = 32

SUMMARY_KEYS = ("collective_overlap_efficiency", "exposed_collective_frac",
                "hidden_ns", "exposed_ns", "collective_ns",
                "schedulable_overlap", "schedulable_ns",
                "async_pairs_total", "sync_total", "backend_sync_schedule")


def _schedulable(s):
    """An entry's schedulable-overlap score: the compiled-schedule score
    when the schedule priced any collectives, else the record-level
    sequence score ladder captures carry (``sequence_schedulable`` — the
    twins' identity stand-ins never lower to HLO collectives, so only
    the recorded op stream can show their emission-order slack)."""
    if s.get("sync_total", 0) + s.get("async_pairs_total", 0):
        return s.get("schedulable_overlap", 0.0)
    return s.get("sequence_schedulable", s.get("schedulable_overlap", 0.0))


def _render(rows):
    """Column-aligned ASCII table; first row is the header."""
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = []
    for i, r in enumerate(rows):
        lines.append("  ".join(c.ljust(w)
                               for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def _us(ns):
    return f"{ns / 1e3:.2f}us"


def format_gantt(stats, label=""):
    """Text Gantt of one program's collective spans, schedule order per
    computation: bar length ~ estimated collective time, ``#`` the
    portion hidden behind compute scheduled inside the async pair,
    ``=`` the exposed remainder. Sync collectives are all ``=`` by
    construction."""
    pairs = sorted(stats.get("pairs", []),
                   key=lambda p: (p["computation"], p["index"]))
    lines = []
    head = f"schedule timeline{' ' + label if label else ''}: " \
           f"efficiency {stats['collective_overlap_efficiency']:.3f}, " \
           f"exposed {_us(stats['exposed_ns'])} of " \
           f"{_us(stats['collective_ns'])} collective " \
           f"({stats['async_pairs_total']} async pair(s), " \
           f"{stats['sync_total']} sync)"
    lines.append(head)
    if stats.get("backend_sync_schedule"):
        lines.append("  NOTE: fully synchronous schedule — this backend "
                     "(XLA:CPU) emits no async collective pairs; the "
                     "efficiency 0.0 is the honest baseline, not an "
                     "analyzer failure")
    if not pairs:
        lines.append("  (no collectives in this program)")
        return "\n".join(lines)
    scale = max(p["collective_ns"] for p in pairs) or 1.0
    comp = None
    name_w = max(len(p["name"]) for p in pairs)
    for p in pairs:
        if p["computation"] != comp:
            comp = p["computation"]
            lines.append(f"  %{comp}:")
        n = max(1, int(round(BAR_WIDTH * p["collective_ns"] / scale)))
        hidden_cells = int(round(n * (p["hidden_ns"] / p["collective_ns"]))
                           ) if p["collective_ns"] else 0
        bar = "#" * hidden_cells + "=" * (n - hidden_cells)
        detail = (f"hidden {_us(p['hidden_ns'])} / exposed "
                  f"{_us(p['exposed_ns'])}" if p["phase"] == "async"
                  else f"exposed {_us(p['exposed_ns'])}")
        mult = f" x{p['count']}" if p["count"] != 1 else ""
        lines.append(f"    {p['name'].ljust(name_w)} "
                     f"[{bar.ljust(BAR_WIDTH)}] {p['op']}@{p['axis']} "
                     f"{detail} ({p['phase']}){mult}")
    return "\n".join(lines)


def format_program_table(programs):
    """Summary table over ``{entry: stats}``; ``"error"`` records render
    as ERR rows (an unattributable twin must stay visible)."""
    rows = [["entry", "efficiency", "sched", "exposed_frac", "exposed_us",
             "async", "sync", "sync_schedule"]]
    for entry in sorted(programs):
        s = programs[entry]
        if "error" in s:
            rows.append([entry, "ERR: " + str(s["error"])[:60],
                         "", "", "", "", "", ""])
            continue
        rows.append([entry,
                     f"{s['collective_overlap_efficiency']:.3f}",
                     f"{_schedulable(s):.3f}",
                     f"{s['exposed_collective_frac']:.3f}",
                     f"{s['exposed_ns'] / 1e3:.2f}",
                     str(s["async_pairs_total"]), str(s["sync_total"]),
                     "yes" if s.get("backend_sync_schedule") else "no"])
    return _render(rows)


def format_program_diff(progs_a, progs_b):
    """Per-entry flag A/B deltas (B minus A): efficiency up and exposed
    time down is the measured latency-hiding win, and ``d_sched`` is the
    schedulable-overlap delta — the backend-independent evidence that
    the EMISSION ORDER changed (the prefetch-pipelined arm rises above
    the serial arm's score even when a sync-schedule backend keeps both
    measured efficiencies at 0.0). Entries on one side only diff
    against zero."""
    rows = [["entry", "eff(A)", "eff(B)", "d_eff", "sched(A)", "sched(B)",
             "d_sched", "exposed_us(A)", "exposed_us(B)", "d_exposed_us",
             "async(A->B)"]]
    for entry in sorted(set(progs_a) | set(progs_b)):
        a = progs_a.get(entry, {})
        b = progs_b.get(entry, {})
        if "error" in a or "error" in b:
            rows.append([entry, "ERR", "ERR", "", "", "", "", "", "", "",
                         ""])
            continue
        ea = a.get("collective_overlap_efficiency", 0.0)
        eb = b.get("collective_overlap_efficiency", 0.0)
        sa, sb = _schedulable(a), _schedulable(b)
        xa = a.get("exposed_ns", 0.0) / 1e3
        xb = b.get("exposed_ns", 0.0) / 1e3
        rows.append([entry, f"{ea:.3f}", f"{eb:.3f}", f"{eb - ea:+.3f}",
                     f"{sa:.3f}", f"{sb:.3f}", f"{sb - sa:+.3f}",
                     f"{xa:.2f}", f"{xb:.2f}", f"{xb - xa:+.2f}",
                     f"{a.get('async_pairs_total', 0)}->"
                     f"{b.get('async_pairs_total', 0)}"])
    return _render(rows)


_COLLECTIVE_NAMES = ("all-gather", "all-reduce", "reduce-scatter",
                     "all-to-all", "collective-permute")


def correlate_trace(trace_dir, stats):
    """Best-effort correlation of the schedule ESTIMATE against
    measured collective span wall-times from a ``jax.profiler.trace``
    directory (``**/*.trace.json.gz`` chrome-trace shards): sums the
    ``dur`` of complete events whose names carry a collective op
    substring. Returns ``{"measured_collective_ns", "events",
    "estimate_collective_ns", "measured_over_estimate"}`` or ``None``
    when the directory holds no usable trace."""
    shards = sorted(glob.glob(os.path.join(trace_dir, "**",
                                           "*.trace.json.gz"),
                              recursive=True))
    shards += sorted(glob.glob(os.path.join(trace_dir, "**",
                                            "*.trace.json"),
                               recursive=True))
    measured_us = 0.0
    n_events = 0
    for shard in shards:
        try:
            opener = gzip.open if shard.endswith(".gz") else open
            with opener(shard, "rt") as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        for ev in data.get("traceEvents", []):
            name = str(ev.get("name", "")).lower()
            if ev.get("dur") is None:
                continue
            if any(op in name for op in _COLLECTIVE_NAMES):
                measured_us += float(ev["dur"])
                n_events += 1
    if not n_events:
        return None
    measured_ns = measured_us * 1e3
    est = stats["collective_ns"]
    return {"measured_collective_ns": measured_ns, "events": n_events,
            "estimate_collective_ns": est,
            "measured_over_estimate": (measured_ns / est) if est
            else None}


def _ladder_programs(configs):
    import jax
    jax.config.update("jax_platforms", "cpu")  # twins are smoke-scale
    from paddle_tpu.analysis import ladder
    out = {}
    for name, rows in ladder.attribute_overlap(configs=configs).items():
        for pi, stats in enumerate(rows):
            label = name if len(rows) == 1 else f"{name}#{pi}"
            out[label] = stats
    return out


def _capture_programs(path):
    with open(path) as f:
        data = json.load(f)
    return data.get("programs", data if isinstance(data, dict) else {})


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render collective overlap schedule timelines; "
                    "--diff compares two captures (flag A/B)")
    ap.add_argument("--ladder", action="store_true",
                    help="attribute the benchmark ladder's program twins")
    ap.add_argument("--configs", default=None,
                    help="comma list of ladder configs (default: all)")
    ap.add_argument("--hlo", metavar="FILE",
                    help="analyze a compiled HLO text dump")
    ap.add_argument("--diff", nargs=2, metavar=("A.json", "B.json"),
                    help="per-entry efficiency/exposed deltas (B minus "
                    "A) between two captures — the flag on/off view")
    ap.add_argument("--out", metavar="JSON",
                    help="write the analyzed programs as a capture "
                    "(feed a later --diff)")
    ap.add_argument("--trace", metavar="DIR",
                    help="jax.profiler trace directory to correlate "
                    "measured collective span wall-times against the "
                    "schedule estimate")
    ap.add_argument("--gantt", action="store_true",
                    help="also render the per-collective schedule "
                    "timeline for every entry (default for --hlo)")
    args = ap.parse_args(argv)

    sources = [bool(args.ladder), bool(args.hlo), bool(args.diff)]
    if sum(sources) != 1:
        ap.error("pick exactly one source: --ladder, --hlo FILE, or "
                 "--diff A.json B.json")

    if args.diff:
        if args.out:
            ap.error("--out records a single capture; it does not "
                     "combine with --diff")
        progs_a = _capture_programs(args.diff[0])
        progs_b = _capture_programs(args.diff[1])
        print(f"overlap deltas (B={args.diff[1]} minus A={args.diff[0]}):")
        if progs_a or progs_b:
            print(format_program_diff(progs_a, progs_b))
        else:
            print("no overlap attributions on either side")
        return 1 if any("error" in s for s in
                        list(progs_a.values()) + list(progs_b.values())) \
            else 0

    if args.hlo:
        from paddle_tpu.observability import overlap
        with open(args.hlo) as f:
            stats = overlap.overlap_stats(f.read())
        programs = {os.path.basename(args.hlo): stats}
        gantt = True
    else:
        configs = args.configs.split(",") if args.configs else None
        programs = _ladder_programs(configs)
        gantt = args.gantt

    if args.out:
        with open(args.out, "w") as f:
            json.dump({"programs": programs}, f, indent=1)

    if programs:
        print(format_program_table(programs))
    else:
        print("no programs in this source")
    if gantt:
        for entry in sorted(programs):
            if "error" in programs[entry]:
                continue
            print()
            print(format_gantt(programs[entry], label=entry))

    if args.trace:
        total = {"collective_ns": sum(
            s.get("collective_ns", 0.0) for s in programs.values()
            if "error" not in s)}
        corr = correlate_trace(args.trace, total)
        print()
        if corr is None:
            print(f"trace correlation: no collective spans found under "
                  f"{args.trace} (no *.trace.json[.gz] shards, or the "
                  f"profile carries no collective events)")
        else:
            ratio = corr["measured_over_estimate"]
            print(f"trace correlation: measured collective wall-time "
                  f"{_us(corr['measured_collective_ns'])} over "
                  f"{corr['events']} span(s) vs schedule estimate "
                  f"{_us(corr['estimate_collective_ns'])}"
                  + (f" (measured/estimate {ratio:.2f}x)"
                     if ratio is not None else ""))

    return 1 if any("error" in s for s in programs.values()) else 0


if __name__ == "__main__":
    sys.exit(main())
