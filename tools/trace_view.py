"""Merge multi-rank/multi-process run-logs into one chrome-trace.

Every process in a run (trainer ranks, the PS server, a serving
replica) writes its own JSONL run-log (``observability/runlog.py``).
This tool merges any number of them into a single ``chrome://tracing``
/ Perfetto JSON file:

- each (file, process-tag) pair becomes a chrome *process* track,
  labeled from its manifest (``run_id`` / ``rank`` / ``pid``);
- clocks are aligned via each manifest's (wall, monotonic) anchor pair,
  so logs from processes — or hosts — with different monotonic bases
  land on one wall-clock timeline;
- spans keep their (trace, span, parent) ids in ``args``; span *links*
  (a serving batch serving N request traces) become chrome flow events
  (``ph: s/f``), so clicking a request's arrow lands on the batch and
  device step that served it;
- discrete events (checkpoint publishes, PS retries, fault injections,
  step stats) render as instant events on their process track.

Usage:
    python tools/trace_view.py RUNLOG.jsonl [...] -o trace.json
    python tools/trace_view.py logs/*.jsonl --trace <16-hex-trace-id>
    python tools/trace_view.py logs/*.jsonl --stats

``--trace`` restricts the output to one trace id plus everything
reachable from it through parent edges and links — the "show me this
p99 request" view. ``--stats`` prints a per-trace/per-process summary
instead of writing a file.

The module doubles as a library: ``load_events``, ``build_chrome_trace``
and ``connected_spans`` are importable (the test suite reconstructs
cross-process traces through them).
"""
import argparse
import collections
import json
import os
import re
import sys

# rotated run-log parts (<base>.partN.jsonl, observability/runlog.py
# max_bytes rolling) merge back onto their base file's process track
_PART_RE = re.compile(r"\.part\d+(\.jsonl)?$")


def _base_file(path):
    if path.endswith(".jsonl"):
        return _PART_RE.sub(r"\1", path)
    return _PART_RE.sub("", path)


def load_events(paths):
    """Read run-log files into a flat event list; each event is tagged
    ``_file`` (source path, with rotation parts folded onto their base
    file so a rolled log stays ONE process track) and ``_offset_ns``
    (monotonic->wall clock offset from its file's manifest, 0 when
    absent). Unparseable lines (the torn last line of a crashed writer)
    are skipped, counted in the returned ``(events, n_bad)``."""
    events, n_bad = [], 0
    for path in paths:
        offset = 0
        tag = _base_file(path)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    n_bad += 1
                    continue
                if rec.get("kind") == "manifest":
                    try:
                        offset = int(rec["time"] * 1e9) - int(rec["mono_ns"])
                    except (KeyError, TypeError):
                        offset = 0
                rec["_file"] = tag
                rec["_offset_ns"] = offset
                events.append(rec)
    return events, n_bad


def _span_key(rec):
    return (rec.get("trace"), rec.get("span"))


def spans_by_id(events):
    """{(trace, span): span-record} over all loaded span events."""
    return {_span_key(r): r for r in events if r.get("kind") == "span"}


def _links_of(rec):
    """Linked (trace, span) keys of a span record (from the ``links``
    attr: a list of "trace:span" hex strings)."""
    out = []
    for ln in (rec.get("attrs") or {}).get("links", []) or []:
        parts = str(ln).split(":")
        if len(parts) == 2:
            out.append((parts[0], parts[1]))
    return out


def connected_spans(events, trace_id):
    """Every span reachable from ``trace_id``: same-trace spans, plus
    spans connected through links (in either direction), transitively —
    the full cross-process story of one request/push/save. Returns span
    records sorted by start time."""
    spans = [r for r in events if r.get("kind") == "span"]
    by_trace = collections.defaultdict(list)
    link_edges = collections.defaultdict(set)  # trace -> linked traces
    for r in spans:
        by_trace[r["trace"]].append(r)
        for (lt, _ls) in _links_of(r):
            link_edges[r["trace"]].add(lt)
            link_edges[lt].add(r["trace"])
    seen, frontier = set(), [str(trace_id)]
    while frontier:
        t = frontier.pop()
        if t in seen:
            continue
        seen.add(t)
        frontier.extend(link_edges.get(t, ()))
    out = [r for t in seen for r in by_trace.get(t, [])]
    return sorted(out, key=lambda r: r.get("t0", 0))


def _proc_label(manifest):
    if manifest is None:
        return "unknown"
    bits = [str(manifest.get("run_id") or "run"),
            f"rank{manifest.get('rank', '?')}",
            f"pid{manifest.get('pid', '?')}"]
    if manifest.get("process") and manifest["process"] != "main":
        bits.append(manifest["process"])
    return "/".join(bits)


def build_chrome_trace(events, trace_filter=None):
    """Chrome-trace dict (``{"traceEvents": [...]}``) from loaded
    run-log events. ``trace_filter`` keeps only spans connected to that
    trace id (events/instants always pass)."""
    keep = None
    if trace_filter is not None:
        keep = {_span_key(r) for r in connected_spans(events, trace_filter)}

    # one chrome pid per (file, process tag); manifests name them
    pids = {}
    manifests = {}
    out = []

    def _pid(rec):
        key = (rec["_file"], rec.get("process") or "main")
        if key not in pids:
            pids[key] = len(pids) + 1
            m = manifests.get(rec["_file"])
            label = _proc_label(m)
            if rec.get("process") and rec["process"] != "main":
                label += f"/{rec['process']}"
            out.append({"name": "process_name", "ph": "M", "pid": pids[key],
                        "args": {"name": label}})
        return pids[key]

    for rec in events:
        kind = rec.get("kind")
        if kind == "manifest":
            manifests[rec["_file"]] = rec
            continue
    flow_id = [0]

    span_index = spans_by_id(events)
    for rec in events:
        kind = rec.get("kind")
        if kind == "span":
            if keep is not None and _span_key(rec) not in keep:
                continue
            pid = _pid(rec)
            ts_us = (rec["t0"] + rec["_offset_ns"]) / 1e3
            args = {"trace": rec.get("trace"), "span": rec.get("span")}
            if rec.get("parent"):
                args["parent"] = rec["parent"]
            args.update(rec.get("attrs") or {})
            ev = {"name": rec["name"], "cat": rec.get("cat", "user"),
                  "ph": "X", "ts": ts_us, "dur": max(rec["dur"], 1) / 1e3,
                  "pid": pid, "tid": rec.get("tid", 0), "args": args}
            out.append(ev)
            # links -> chrome flow arrows (start at this span, finish at
            # the linked span), connecting traces across processes
            for lk in _links_of(rec):
                target = span_index.get(lk)
                if target is None or (keep is not None
                                      and lk not in keep):
                    continue
                flow_id[0] += 1
                fid = flow_id[0]
                out.append({"name": "link", "cat": "link", "ph": "s",
                            "id": fid, "pid": pid,
                            "tid": rec.get("tid", 0), "ts": ts_us})
                out.append({"name": "link", "cat": "link", "ph": "f",
                            "bp": "e", "id": fid, "pid": _pid(target),
                            "tid": target.get("tid", 0),
                            "ts": (target["t0"] + target["_offset_ns"])
                            / 1e3})
        elif kind == "event":
            pid = _pid(rec)
            out.append({"name": rec.get("event", "event"), "cat": "event",
                        "ph": "i", "s": "p", "pid": pid, "tid": 0,
                        "ts": (rec.get("t", 0) + rec["_offset_ns"]) / 1e3,
                        "args": {k: v for k, v in rec.items()
                                 if not k.startswith("_")
                                 and k not in ("kind", "t")}})
    return {"traceEvents": out}


def state_residency(events):
    """Pod-wide state residency from the LAST ``memory_snapshot`` event
    of each process log: ``(per_category_bytes, n_ranks)``. Each rank's
    ledger (``memory.runlog_snapshot``, rank-tagged) counts what THAT
    process holds; summing the latest snapshot per rank is the
    multi-host total a single-process scrape can't see."""
    last = {}
    for r in events:
        if r.get("kind") == "event" and r.get("event") == "memory_snapshot":
            key = (r["_file"], r.get("rank", r.get("process", "0")))
            if key not in last or r.get("t", 0) >= last[key].get("t", 0):
                last[key] = r
    cats = collections.Counter()
    for r in last.values():
        for cat, slot in ((r.get("state") or {}).get("categories")
                          or {}).items():
            cats[cat] += int(slot.get("bytes", 0))
    return dict(cats), len(last)


def reform_timeline(events):
    """The pod's generation history from ``pod_reform`` run-log events:
    one entry per generation (every rank logs the same transition —
    grouped by gen, earliest wall time wins), ordered by generation.
    Each entry: ``{"gen", "direction", "old_world", "new_world", "t"
    (wall seconds), "took_s" (slowest rank's reform wall time)}`` — the
    kill→shrink→heal→grow lifecycle as a timeline."""
    by_gen = {}
    for r in events:
        if r.get("kind") != "event" or r.get("event") != "pod_reform":
            continue
        gen = r.get("gen")
        wall = (r.get("t", 0) + r["_offset_ns"]) / 1e9
        cur = by_gen.setdefault(gen, {
            "gen": gen, "direction": r.get("direction"),
            "old_world": r.get("old_world"), "new_world": r.get("new_world",
                                                               r.get("world")),
            "t": wall, "took_s": r.get("took_s", 0) or 0})
        cur["t"] = min(cur["t"], wall)
        cur["took_s"] = max(cur["took_s"], r.get("took_s", 0) or 0)
        if cur.get("direction") is None:
            cur["direction"] = r.get("direction")
    return [by_gen[g] for g in sorted(by_gen, key=lambda g: (g is None, g))]


def print_stats(events, n_bad, file=None):
    file = file if file is not None else sys.stdout
    spans = [r for r in events if r.get("kind") == "span"]
    evs = [r for r in events if r.get("kind") == "event"]
    manifests = [r for r in events if r.get("kind") == "manifest"]
    traces = collections.Counter(r["trace"] for r in spans)
    print(f"{len(manifests)} process log(s), {len(spans)} spans, "
          f"{len(evs)} events, {len(traces)} traces"
          + (f", {n_bad} unparseable line(s)" if n_bad else ""),
          file=file)
    for m in manifests:
        print(f"  {_proc_label(m)}  <- {os.path.basename(m['_file'])}",
              file=file)
    by_event = collections.Counter(r.get("event") for r in evs)
    if by_event:
        print("  events: " + ", ".join(f"{k}={v}" for k, v in
                                       sorted(by_event.items())),
              file=file)
    cats, n_ranks = state_residency(events)
    if cats:
        total = sum(cats.values())
        print(f"  state residency (last snapshot per rank, summed over "
              f"{n_ranks} rank(s), {total / 1e6:.3f} MB): "
              + ", ".join(f"{c}={b / 1e6:.3f}MB"
                          for c, b in sorted(cats.items(),
                                             key=lambda kv: -kv[1])),
              file=file)
    timeline = reform_timeline(events)
    if timeline:
        t0 = min(e["t"] for e in timeline)
        print("  reform timeline:", file=file)
        for e in timeline:
            worlds = (f"world {e['old_world']}->{e['new_world']}"
                      if e.get("old_world") is not None
                      else f"world {e['new_world']}")
            print(f"    gen {e['gen']}: {e.get('direction') or '?':<6} "
                  f"{worlds} at +{e['t'] - t0:.3f}s "
                  f"(reform {e['took_s']:.3f}s)", file=file)
    top = traces.most_common(5)
    if top:
        print("  largest traces: " + ", ".join(
            f"{t[:8]}…×{n}" for t, n in top), file=file)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge run-log JSONL files into one chrome-trace")
    ap.add_argument("logs", nargs="+", help="run-log .jsonl files")
    ap.add_argument("-o", "--out", default="trace.json",
                    help="chrome-trace output path (default trace.json)")
    ap.add_argument("--trace", help="restrict to one trace id (16-hex) "
                    "plus everything linked to it")
    ap.add_argument("--stats", action="store_true",
                    help="print a summary instead of writing the trace")
    args = ap.parse_args(argv)

    events, n_bad = load_events(args.logs)
    if args.stats:
        print_stats(events, n_bad)
        return 0
    trace = build_chrome_trace(events, trace_filter=args.trace)
    with open(args.out, "w") as f:
        json.dump(trace, f)
    n_spans = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    print(f"wrote {args.out}: {n_spans} spans from {len(args.logs)} "
          f"log(s)" + (f" ({n_bad} unparseable line(s) skipped)"
                       if n_bad else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
