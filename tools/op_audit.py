#!/usr/bin/env python
"""Op-coverage audit: map every operator type registered by the
reference (`/root/reference/paddle/fluid/operators`) to its paddle_tpu
disposition and FAIL on unmapped entries.

Dispositions:
  symbol    — implemented: a dotted paddle_tpu symbol exists (verified
              by import at audit time)
  delegated — the capability is provided by XLA/PJRT/jax or by a
              different architectural seam (reason recorded)
  deferred  — deliberately out of scope (reason recorded; SURVEY §7.9)

Usage: python tools/op_audit.py [--reference DIR] [--json OUT]
Exit 0 iff zero ops are unmapped. tests/test_op_audit.py runs this.
"""
import argparse
import importlib
import json
import os
import re
import sys

DEFAULT_REF = "/root/reference/paddle/fluid/operators"

# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

_PATTERNS = [
    re.compile(r"REGISTER_OPERATOR\(\s*([a-z0-9_]+)"),
    re.compile(r"REGISTER_OP_WITHOUT_GRADIENT\(\s*([a-z0-9_]+)"),
    re.compile(r"REGISTER_OP_CPU_KERNEL\(\s*([a-z0-9_]+)"),
    re.compile(r"REGISTER_OP_VERSION\(\s*([a-z0-9_]+)"),
]
_CAMEL = re.compile(r"REGISTER_ACTIVATION_OP_MAKER\(\s*([A-Za-z0-9_]+)")


def extract_ops(ref_dir):
    ops = set()
    for root, _dirs, files in os.walk(ref_dir):
        for fn in files:
            if not fn.endswith((".cc", ".cu", ".h")):
                continue
            try:
                text = open(os.path.join(root, fn),
                            errors="replace").read()
            except OSError:
                continue
            for pat in _PATTERNS:
                ops.update(pat.findall(text))
            for camel in _CAMEL.findall(text):
                ops.add(re.sub(r"(?<!^)(?=[A-Z])", "_", camel).lower())
    junk = {"op_name", "op_type", "o_p__n_a_m_e"}  # macro parameters
    return sorted(o for o in ops
                  if not o.endswith("_grad") and o not in junk)


# ---------------------------------------------------------------------------
# resolution
# ---------------------------------------------------------------------------

# probed in order for an attribute of the op's exact name
_PROBE_MODULES = [
    "paddle_tpu",
    "paddle_tpu.ops",
    "paddle_tpu.nn.functional",
    "paddle_tpu.linalg",
    "paddle_tpu.vision.ops",
    "paddle_tpu.metric",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.collective",
    "paddle_tpu.static.nn",
    "paddle_tpu.ops.sequence",
    "paddle_tpu.text",
]

_HERE = os.path.dirname(os.path.abspath(__file__))
with open(os.path.join(_HERE, "op_map.json")) as _f:
    _MAP = json.load(_f)

EXPLICIT = _MAP["explicit"]      # op -> dotted symbol
DELEGATED = _MAP["delegated"]    # op -> reason
DEFERRED = _MAP["deferred"]      # op -> reason


def _resolve_symbol(path):
    mod_name, _, attr = path.rpartition(".")
    try:
        mod = importlib.import_module(mod_name)
    except ImportError:
        return False
    obj = mod
    for part in attr.split("."):
        if not hasattr(obj, part):
            return False
        obj = getattr(obj, part)
    return True


def audit(ref_dir):
    ops = extract_ops(ref_dir)
    rows = {}
    probe = []
    for mod_name in _PROBE_MODULES:
        try:
            importlib.import_module(mod_name)
            probe.append(mod_name)
        except ImportError:
            pass
    for op in ops:
        if op in EXPLICIT:
            path = EXPLICIT[op]
            rows[op] = ({"disposition": "symbol", "symbol": path}
                        if _resolve_symbol(path) else
                        {"disposition": "BROKEN",
                         "symbol": path,
                         "note": "mapped symbol does not import"})
            continue
        if op in DELEGATED:
            rows[op] = {"disposition": "delegated",
                        "reason": DELEGATED[op]}
            continue
        if op in DEFERRED:
            rows[op] = {"disposition": "deferred", "reason": DEFERRED[op]}
            continue
        found = None
        for mod_name in probe:
            mod = sys.modules[mod_name]
            if hasattr(mod, op):
                found = f"{mod_name}.{op}"
                break
        if found:
            rows[op] = {"disposition": "symbol", "symbol": found}
        else:
            rows[op] = {"disposition": "UNMAPPED"}
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default=DEFAULT_REF)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()
    rows = audit(args.reference)
    counts = {}
    for r in rows.values():
        counts[r["disposition"]] = counts.get(r["disposition"], 0) + 1
    report = {"total": len(rows), "counts": counts, "ops": rows}
    text = json.dumps(report, indent=1, sort_keys=True)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text + "\n")
    bad = [op for op, r in rows.items()
           if r["disposition"] in ("UNMAPPED", "BROKEN")]
    print(f"op audit: {len(rows)} ops — "
          + ", ".join(f"{k}={v}" for k, v in sorted(counts.items())))
    if bad:
        print("UNMAPPED/BROKEN:")
        for op in bad:
            print(f"  {op}: {rows[op]}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
