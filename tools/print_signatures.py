"""Print the public API surface as a stable spec (reference:
`tools/print_signatures.py` — generates paddle/fluid/API.spec, the frozen
API contract CI diffs against).

Usage:
    python tools/print_signatures.py             # print to stdout
    python tools/print_signatures.py --write     # refresh API.spec
"""
import argparse
import importlib
import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODULES = [
    "paddle_tpu",
    "paddle_tpu.nn",
    "paddle_tpu.nn.functional",
    "paddle_tpu.nn.initializer",
    "paddle_tpu.ops",
    "paddle_tpu.optimizer",
    "paddle_tpu.optimizer.lr",
    "paddle_tpu.static",
    "paddle_tpu.jit",
    "paddle_tpu.jit.xla_flags",
    "paddle_tpu.analysis",
    "paddle_tpu.analysis.concurrency",
    "paddle_tpu.analysis.lockwatch",
    "paddle_tpu.analysis.shardcheck",
    "paddle_tpu.amp",
    "paddle_tpu.io",
    "paddle_tpu.metric",
    "paddle_tpu.linalg",
    "paddle_tpu.vision.models",
    "paddle_tpu.vision.transforms",
    "paddle_tpu.models",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.fleet",
    "paddle_tpu.distributed.pod",
    "paddle_tpu.distributed.ps",
    "paddle_tpu.quantization",
    "paddle_tpu.sparsity",
    "paddle_tpu.inference",
    "paddle_tpu.observability",
    "paddle_tpu.observability.memory",
    "paddle_tpu.observability.overlap",
    "paddle_tpu.recompute",
    "paddle_tpu.serving",
    "paddle_tpu.checkpoint",
    "paddle_tpu.checkpoint.multihost",
    "paddle_tpu.testing",
    "paddle_tpu.testing.faults",
    "paddle_tpu.testing.virtual_pod",
    "paddle_tpu.onnx",
    "paddle_tpu.incubate",
    "paddle_tpu.text",
    "paddle_tpu.hapi",
]

SPEC_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "API.spec")


def _sig_of(obj):
    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        sig = "(...)"
    return sig


def collect():
    lines = []
    for modname in MODULES:
        try:
            mod = importlib.import_module(modname)
        except ImportError as e:
            lines.append(f"{modname} IMPORT-ERROR {e}")
            continue
        names = getattr(mod, "__all__", None)
        if names is None:
            names = [n for n in dir(mod) if not n.startswith("_")]
        for name in sorted(set(names)):
            obj = getattr(mod, name, None)
            if obj is None or inspect.ismodule(obj):
                continue
            if inspect.isclass(obj):
                lines.append(f"{modname}.{name} class{_sig_of(obj)}")
                for mname in sorted(dir(obj)):
                    if mname.startswith("_"):
                        continue
                    raw = inspect.getattr_static(obj, mname, None)
                    # getattr_static sees class/static/plain methods alike
                    # (callable(classmethod) is False; vars() misses
                    # inherited methods) — properties freeze as attributes
                    if isinstance(raw, (classmethod, staticmethod)):
                        meth = raw.__func__
                        kind = ("classmethod"
                                if isinstance(raw, classmethod)
                                else "staticmethod")
                    elif inspect.isfunction(raw):
                        meth, kind = raw, "method"
                    elif isinstance(raw, property):
                        lines.append(
                            f"{modname}.{name}.{mname} property")
                        continue
                    else:
                        continue
                    lines.append(
                        f"{modname}.{name}.{mname} {kind}{_sig_of(meth)}")
            elif callable(obj):
                lines.append(f"{modname}.{name} function{_sig_of(obj)}")
    return sorted(set(lines))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--write", action="store_true",
                    help="refresh API.spec in place")
    args = ap.parse_args()
    lines = collect()
    text = "\n".join(lines) + "\n"
    if args.write:
        with open(SPEC_PATH, "w") as f:
            f.write(text)
        print(f"wrote {len(lines)} entries to {SPEC_PATH}")
    else:
        sys.stdout.write(text)


if __name__ == "__main__":
    main()
