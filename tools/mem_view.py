#!/usr/bin/env python
"""HBM memory viewer + budget gate over the attribution layer.

Renders per-entry program memory breakdowns (argument / output / temp /
alias / generated-code / peak bytes, ``observability.memory``) and the
framework-state residency ledger as tables, and optionally checks every
program peak against an HBM budget — the pre-flight answer to "does
this config fit the chip?" that today is discovered by OOM-ing.

Sources (pick one):

    # attribute the benchmark ladder's verified program twins
    python tools/mem_view.py --ladder [--configs resnet,zero3]

    # render a recorded snapshot (a flight dump's "memory" section, a
    # run-log memory_snapshot event, or observability.memory.snapshot()
    # written as JSON)
    python tools/mem_view.py --snapshot dump.json

    # gate: exit 3 when any program peak exceeds the budget
    python tools/mem_view.py --ladder --budget-mb 16000

Exit codes: 0 ok, 1 usage/attribution error, 3 budget exceeded.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

KINDS = ("argument", "output", "temp", "alias", "generated_code", "peak")


def _mb(nbytes):
    return nbytes / (1024 * 1024)


def _render(rows):
    """Column-aligned ASCII table; first row is the header, followed by
    a dash separator."""
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = []
    for i, r in enumerate(rows):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def format_program_table(programs):
    """ASCII table over ``{entry: stats}`` records (MB, 3 decimals);
    records carrying an ``"error"`` key render as ERR rows."""
    rows = [["entry"] + [f"{k}_mb" for k in KINDS]]
    for entry in sorted(programs):
        stats = programs[entry]
        if "error" in stats:
            rows.append([entry, "ERR: " + str(stats["error"])[:60]]
                        + [""] * (len(KINDS) - 1))
            continue
        rows.append([entry] + [f"{_mb(stats[f'{k}_bytes']):.3f}"
                               for k in KINDS])
    return _render(rows)


def format_state_table(state):
    """ASCII table over a ledger/snapshot ``state`` section: per-category
    resident (per-rank) and global bytes."""
    cats = state.get("categories", {})
    rows = [["category", "resident_mb", "global_mb", "tensors"]]
    for cat in sorted(cats, key=lambda c: -cats[c]["bytes"]):
        s = cats[cat]
        rows.append([cat, f"{_mb(s['bytes']):.3f}",
                     f"{_mb(s['global_bytes']):.3f}", str(s["count"])])
    rows.append(["TOTAL", f"{_mb(state.get('total_bytes', 0)):.3f}",
                 f"{_mb(state.get('total_global_bytes', 0)):.3f}", ""])
    return _render(rows)


def check_budget(programs, budget_mb):
    """``(ok, over)`` where ``over`` lists ``(entry, peak_mb)`` for every
    program whose peak exceeds the budget (error records count as over —
    an unattributable program cannot be certified to fit)."""
    over = []
    for entry, stats in sorted(programs.items()):
        if "error" in stats:
            over.append((entry, None))
        elif _mb(stats["peak_bytes"]) > budget_mb:
            over.append((entry, _mb(stats["peak_bytes"])))
    return not over, over


def _ladder_programs(configs):
    import jax
    jax.config.update("jax_platforms", "cpu")  # twins are smoke-scale
    from paddle_tpu.analysis import ladder
    out = {}
    for name, rows in ladder.attribute_memory(configs=configs).items():
        for pi, stats in enumerate(rows):
            label = name if len(rows) == 1 else f"{name}#{pi}"
            out[label] = stats
    return out


def _snapshot_sections(path):
    """(programs, state) from a snapshot-ish JSON: accepts a raw
    ``memory.snapshot()``, a flight dump (reads its ``memory`` key), or
    a run-log memory_snapshot event."""
    with open(path) as f:
        data = json.load(f)
    if "memory" in data and isinstance(data["memory"], dict):
        data = data["memory"]  # flight dump
    return data.get("programs", {}), data.get("state", {})


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render per-program HBM attribution + state "
                    "residency tables; optionally gate on a budget")
    ap.add_argument("--ladder", action="store_true",
                    help="attribute the benchmark ladder's program twins")
    ap.add_argument("--configs", default=None,
                    help="comma list of ladder configs (default: all)")
    ap.add_argument("--snapshot", metavar="JSON",
                    help="render a recorded memory snapshot / flight "
                    "dump instead of attributing the ladder")
    ap.add_argument("--budget-mb", type=float, default=None,
                    help="fail (exit 3) when any program peak exceeds "
                    "this many MB")
    args = ap.parse_args(argv)

    if bool(args.ladder) == bool(args.snapshot):
        ap.error("pick exactly one source: --ladder or --snapshot FILE")

    state = None
    if args.snapshot:
        programs, state = _snapshot_sections(args.snapshot)
    else:
        configs = args.configs.split(",") if args.configs else None
        programs = _ladder_programs(configs)

    if programs:
        print(format_program_table(programs))
    else:
        print("no program attributions in this source")
    if state:
        print()
        print(format_state_table(state))

    rc = 0
    if any("error" in s for s in programs.values()):
        rc = 1
    if args.budget_mb is not None:
        ok, over = check_budget(programs, args.budget_mb)
        if ok:
            print(f"\nBUDGET: PASS (every program peak <= "
                  f"{args.budget_mb:g} MB)")
        else:
            for entry, peak in over:
                print(f"\nBUDGET: {entry} "
                      + ("attribution failed" if peak is None
                         else f"peak {peak:.3f} MB > {args.budget_mb:g} MB"))
            print("BUDGET: FAIL")
            rc = 3
    return rc


if __name__ == "__main__":
    sys.exit(main())
