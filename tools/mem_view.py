#!/usr/bin/env python
"""HBM memory viewer + budget gate over the attribution layer.

Renders per-entry program memory breakdowns (argument / output / temp /
alias / generated-code / peak bytes, ``observability.memory``) and the
framework-state residency ledger as tables, and optionally checks every
program peak against an HBM budget — the pre-flight answer to "does
this config fit the chip?" that today is discovered by OOM-ing.

Sources (pick one):

    # attribute the benchmark ladder's verified program twins
    python tools/mem_view.py --ladder [--configs resnet,zero3]

    # render a recorded snapshot (a flight dump's "memory" section, a
    # run-log memory_snapshot event, or observability.memory.snapshot()
    # written as JSON)
    python tools/mem_view.py --snapshot dump.json

    # gate: exit 3 when any program peak exceeds the budget
    python tools/mem_view.py --ladder --budget-mb 16000

    # A/B evidence view: per-entry / per-category deltas between two
    # captures (flight dumps, memory.snapshot() files, or --out files)
    # — the one-command remat-on-vs-off comparison
    python tools/mem_view.py --diff before.json after.json

    # record a capture for a later --diff
    python tools/mem_view.py --ladder --out capture.json

Exit codes: 0 ok, 1 usage/attribution error, 3 budget exceeded.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

KINDS = ("argument", "output", "temp", "alias", "generated_code",
         "host_offload", "peak")


def _mb(nbytes):
    return nbytes / (1024 * 1024)


def _render(rows):
    """Column-aligned ASCII table; first row is the header, followed by
    a dash separator."""
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = []
    for i, r in enumerate(rows):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def format_program_table(programs):
    """ASCII table over ``{entry: stats}`` records (MB, 3 decimals);
    records carrying an ``"error"`` key render as ERR rows."""
    rows = [["entry"] + [f"{k}_mb" for k in KINDS]]
    for entry in sorted(programs):
        stats = programs[entry]
        if "error" in stats:
            rows.append([entry, "ERR: " + str(stats["error"])[:60]]
                        + [""] * (len(KINDS) - 1))
            continue
        rows.append([entry] + [f"{_mb(stats.get(f'{k}_bytes', 0)):.3f}"
                               for k in KINDS])
    return _render(rows)


def _fmt_delta(nbytes):
    return f"{_mb(nbytes):+.3f}"


def format_program_diff(progs_a, progs_b):
    """Per-entry, per-kind deltas (B minus A) over the union of entries;
    an entry present on one side only renders its bytes one-sided with
    the delta against zero."""
    rows = [["entry"] + [f"{k}_mb(A)" for k in ("peak",)]
            + [f"{k}_mb(B)" for k in ("peak",)]
            + [f"d_{k}_mb" for k in KINDS]]
    for entry in sorted(set(progs_a) | set(progs_b)):
        a = progs_a.get(entry, {})
        b = progs_b.get(entry, {})
        if "error" in a or "error" in b:
            rows.append([entry, "ERR", "ERR"] + [""] * len(KINDS))
            continue
        rows.append(
            [entry,
             f"{_mb(a.get('peak_bytes', 0)):.3f}",
             f"{_mb(b.get('peak_bytes', 0)):.3f}"]
            + [_fmt_delta(b.get(f"{k}_bytes", 0) - a.get(f"{k}_bytes", 0))
               for k in KINDS])
    return _render(rows)


def format_state_diff(state_a, state_b):
    """Per-category resident/global deltas (B minus A) plus totals."""
    cats_a = state_a.get("categories", {})
    cats_b = state_b.get("categories", {})
    rows = [["category", "resident_mb(A)", "resident_mb(B)",
             "d_resident_mb", "d_global_mb"]]
    names = sorted(set(cats_a) | set(cats_b),
                   key=lambda c: -(cats_b.get(c, cats_a.get(c))["bytes"]))
    for cat in names:
        a = cats_a.get(cat, {"bytes": 0, "global_bytes": 0})
        b = cats_b.get(cat, {"bytes": 0, "global_bytes": 0})
        rows.append([cat, f"{_mb(a['bytes']):.3f}", f"{_mb(b['bytes']):.3f}",
                     _fmt_delta(b["bytes"] - a["bytes"]),
                     _fmt_delta(b["global_bytes"] - a["global_bytes"])])
    rows.append(["TOTAL",
                 f"{_mb(state_a.get('total_bytes', 0)):.3f}",
                 f"{_mb(state_b.get('total_bytes', 0)):.3f}",
                 _fmt_delta(state_b.get("total_bytes", 0)
                            - state_a.get("total_bytes", 0)),
                 _fmt_delta(state_b.get("total_global_bytes", 0)
                            - state_a.get("total_global_bytes", 0))])
    return _render(rows)


def format_state_table(state):
    """ASCII table over a ledger/snapshot ``state`` section: per-category
    resident (per-rank) and global bytes."""
    cats = state.get("categories", {})
    rows = [["category", "resident_mb", "global_mb", "tensors"]]
    for cat in sorted(cats, key=lambda c: -cats[c]["bytes"]):
        s = cats[cat]
        rows.append([cat, f"{_mb(s['bytes']):.3f}",
                     f"{_mb(s['global_bytes']):.3f}", str(s["count"])])
    rows.append(["TOTAL", f"{_mb(state.get('total_bytes', 0)):.3f}",
                 f"{_mb(state.get('total_global_bytes', 0)):.3f}", ""])
    return _render(rows)


def check_budget(programs, budget_mb):
    """``(ok, over)`` where ``over`` lists ``(entry, peak_mb)`` for every
    program whose peak exceeds the budget (error records count as over —
    an unattributable program cannot be certified to fit)."""
    over = []
    for entry, stats in sorted(programs.items()):
        if "error" in stats:
            over.append((entry, None))
        elif _mb(stats["peak_bytes"]) > budget_mb:
            over.append((entry, _mb(stats["peak_bytes"])))
    return not over, over


def _ladder_programs(configs):
    import jax
    jax.config.update("jax_platforms", "cpu")  # twins are smoke-scale
    from paddle_tpu.analysis import ladder
    out = {}
    for name, rows in ladder.attribute_memory(configs=configs).items():
        for pi, stats in enumerate(rows):
            label = name if len(rows) == 1 else f"{name}#{pi}"
            out[label] = stats
    return out


def _snapshot_sections(path):
    """(programs, state) from a snapshot-ish JSON: accepts a raw
    ``memory.snapshot()``, a flight dump (reads its ``memory`` key), or
    a run-log memory_snapshot event."""
    with open(path) as f:
        data = json.load(f)
    if "memory" in data and isinstance(data["memory"], dict):
        data = data["memory"]  # flight dump
    return data.get("programs", {}), data.get("state", {})


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render per-program HBM attribution + state "
                    "residency tables; optionally gate on a budget")
    ap.add_argument("--ladder", action="store_true",
                    help="attribute the benchmark ladder's program twins")
    ap.add_argument("--configs", default=None,
                    help="comma list of ladder configs (default: all)")
    ap.add_argument("--snapshot", metavar="JSON",
                    help="render a recorded memory snapshot / flight "
                    "dump instead of attributing the ladder")
    ap.add_argument("--diff", nargs=2, metavar=("A.json", "B.json"),
                    help="side-by-side per-entry/per-category deltas "
                    "(B minus A) between two captures — the remat "
                    "on/off A/B evidence view")
    ap.add_argument("--out", metavar="JSON",
                    help="also write the rendered sections as a "
                    "canonical capture (feed a later --diff)")
    ap.add_argument("--budget-mb", type=float, default=None,
                    help="fail (exit 3) when any program peak exceeds "
                    "this many MB")
    args = ap.parse_args(argv)

    sources = [bool(args.ladder), bool(args.snapshot), bool(args.diff)]
    if sum(sources) != 1:
        ap.error("pick exactly one source: --ladder, --snapshot FILE, "
                 "or --diff A.json B.json")

    if args.diff:
        if args.out:
            ap.error("--out records a single capture; it does not "
                     "combine with --diff")
        progs_a, state_a = _snapshot_sections(args.diff[0])
        progs_b, state_b = _snapshot_sections(args.diff[1])
        print(f"program deltas (B={args.diff[1]} minus A={args.diff[0]}):")
        if progs_a or progs_b:
            print(format_program_diff(progs_a, progs_b))
        else:
            print("no program attributions on either side")
        if state_a or state_b:
            print()
            print("state residency deltas:")
            print(format_state_diff(state_a or {}, state_b or {}))
        rc = 1 if any("error" in s for s in
                      list(progs_a.values()) + list(progs_b.values())) \
            else 0
        if args.budget_mb is not None:
            # the gate judges the AFTER side — a diff invocation with a
            # budget must never pass silently without evaluating it
            ok, over = check_budget(progs_b, args.budget_mb)
            if ok:
                print(f"\nBUDGET(B): PASS (every program peak <= "
                      f"{args.budget_mb:g} MB)")
            else:
                for entry, peak in over:
                    print(f"\nBUDGET(B): {entry} "
                          + ("attribution failed" if peak is None
                             else f"peak {peak:.3f} MB > "
                                  f"{args.budget_mb:g} MB"))
                print("BUDGET(B): FAIL")
                rc = 3
        return rc

    state = None
    if args.snapshot:
        programs, state = _snapshot_sections(args.snapshot)
    else:
        configs = args.configs.split(",") if args.configs else None
        programs = _ladder_programs(configs)

    if args.out:
        with open(args.out, "w") as f:
            json.dump({"programs": programs, "state": state or {}}, f,
                      indent=1)

    if programs:
        print(format_program_table(programs))
    else:
        print("no program attributions in this source")
    if state:
        print()
        print(format_state_table(state))

    rc = 0
    if any("error" in s for s in programs.values()):
        rc = 1
    if args.budget_mb is not None:
        ok, over = check_budget(programs, args.budget_mb)
        if ok:
            print(f"\nBUDGET: PASS (every program peak <= "
                  f"{args.budget_mb:g} MB)")
        else:
            for entry, peak in over:
                print(f"\nBUDGET: {entry} "
                      + ("attribution failed" if peak is None
                         else f"peak {peak:.3f} MB > {args.budget_mb:g} MB"))
            print("BUDGET: FAIL")
            rc = 3
    return rc


if __name__ == "__main__":
    sys.exit(main())
