#!/usr/bin/env python
"""Program verifier + TPU lint CLI — the repo's static-analysis gate.

Reference analog: the C++-side graph checks that keep fluid's ~80 IR
passes and `framework/prune.cc` honest, surfaced as a CI-runnable tool
over the collapsed trace->XLA pipeline.

    python tools/lint_program.py               # --ladder, --source and
                                               # --concurrency (the default
                                               # sweep)
    python tools/lint_program.py --ladder      # verify the benchmark
                                               # ladder's program miniatures
    python tools/lint_program.py --source      # AST lint (nondeterminism in
                                               # traced fns, eager jnp in
                                               # dispatch hot paths)
    python tools/lint_program.py --source paddle_tpu/core/dispatch.py ...
    python tools/lint_program.py --concurrency # lock-order cycles, blocking
                                               # calls under a lock, cv-wait
                                               # discipline over the thread-
                                               # heavy runtime modules

Exit codes: 0 clean, 1 any error-severity finding (warnings print but do
not fail the gate; --strict promotes them). Wired into the verify-skill
recipe and `benchmarks/run_all.py --write-baseline` (a perf baseline must
not be pinned from a program the verifier rejects).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="static analysis over paddle_tpu programs and sources")
    ap.add_argument("--ladder", action="store_true",
                    help="verify the benchmark ladder's program miniatures")
    ap.add_argument("--source", nargs="*", metavar="PATH",
                    help="AST-lint sources (no PATH = the registered "
                    "hot-path files)")
    ap.add_argument("--concurrency", nargs="*", metavar="PATH",
                    help="static concurrency analysis (no PATH = the "
                    "thread-heavy runtime modules under "
                    "distributed/serving/observability/testing)")
    ap.add_argument("--configs", default=None,
                    help="comma list of ladder configs (default: all)")
    ap.add_argument("--strict", action="store_true",
                    help="warnings also fail the gate")
    args = ap.parse_args(argv)

    # no flags = the full default sweep; any flag alone selects its part
    none_selected = (not args.ladder and args.source is None
                     and args.concurrency is None)
    run_ladder = args.ladder or none_selected
    run_source = args.source is not None or none_selected
    run_concurrency = args.concurrency is not None or none_selected

    findings = []
    if run_ladder:
        # the miniatures are smoke-scale: always verify on CPU (the env
        # var alone is not honored once an accelerator plugin is
        # installed; the config update must come before first jax use)
        import jax
        jax.config.update("jax_platforms", "cpu")
        from paddle_tpu.analysis import ERROR, Finding, ladder
        from paddle_tpu.analysis.shardcheck import format_shard_stats
        from paddle_tpu.observability import memory as mem
        configs = args.configs.split(",") if args.configs else None
        # build the twins once, verify without the built-in attribution
        # pass, then attribute here — the stats feed both the gate (an
        # unattributable twin refuses the ladder, like a verify failure)
        # and the per-config hbm_peak column, without building or
        # compiling twins twice
        programs = ladder.build_ladder_programs(configs)
        fs, summary = ladder.verify_ladder(memory=False,
                                           programs=programs)
        findings.extend(fs)
        attribution = ladder.attribute_memory(programs=programs)
        for name, rows in sorted(attribution.items()):
            for pi, s in enumerate(rows):
                if "error" in s:
                    findings.append(Finding(
                        "memory-attribution-failed", ERROR,
                        f"[{name}] program {pi}: {s['error']}"))
        # record-level sharding summary: the stamped collective multiset
        # per twin, rendered as the shard= column (shardcheck's budget
        # findings already rode in through verify_ladder)
        shard_attr = ladder.attribute_sharding(programs=programs)
        # overlap attribution rides the same contract: a verified twin
        # whose schedule cannot be parsed/priced refuses the ladder
        overlap_attr = ladder.attribute_overlap(programs=programs)
        for name, rows in sorted(overlap_attr.items()):
            for pi, s in enumerate(rows):
                if "error" in s:
                    findings.append(Finding(
                        "overlap-attribution-failed", ERROR,
                        f"[{name}] program {pi}: {s['error']}"))
        for name, op_counts in sorted(summary.items()):
            peaks = [("err" if "error" in s
                      else f"{mem.mb(s['peak_bytes']):g}MB")
                     for s in attribution.get(name, [])]
            overlaps = [("err" if "error" in s
                         else "none" if not (s["sync_total"]
                                             + s["async_pairs_total"])
                         else f"{s['collective_overlap_efficiency']:.2f}")
                        for s in overlap_attr.get(name, [])]
            # record-level schedulable score (emission-order slack the
            # stamped collective sequence leaves hideable) — nonzero
            # only for twins that carry collectives; the pipelined
            # zero3_prefetch twin is the one that should read 1.00
            scheds = [f"{s.get('sequence_schedulable', 0.0):.2f}"
                      for s in overlap_attr.get(name, [])]
            shards = [format_shard_stats(s)
                      for s in shard_attr.get(name, [])]
            print(f"ladder[{name}]: {len(op_counts)} program(s), "
                  f"ops={op_counts}, hbm_peak={peaks}, "
                  f"overlap={overlaps}, sched={scheds}, "
                  f"shard={shards}")
    if run_source:
        from paddle_tpu.analysis import lint_source
        findings.extend(lint_source(paths=args.source or None))
    if run_concurrency:
        from paddle_tpu.analysis import check_concurrency
        findings.extend(check_concurrency(paths=args.concurrency or None))

    n_err = sum(f.severity == "error" for f in findings)
    n_warn = sum(f.severity == "warning" for f in findings)
    for f in findings:
        print(f)
    print(f"lint_program: {n_err} error(s), {n_warn} warning(s), "
          f"{len(findings) - n_err - n_warn} info")
    if n_err or (args.strict and n_warn):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
